"""Fleet-scale serving tests (docs/SERVING.md "Fleet"): engine-per-
device replication with least-loaded + health-gated dispatch,
continuous batching vs the group compat mode, the multi-process router
(membership, failover, rolling reload), and fleet /metrics
aggregation.

Determinism rules as in tests/test_overload.py: engine stalls are real
Events the test controls, breaker time is an injected fake clock, and
routing decisions are observed through counters, not timing. Replicas
land on distinct forced-CPU devices (conftest's 8-device shim), so the
per-device placement path is the real one.
"""

import json
import threading
import time
from urllib import request as urlreq

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.models import Actor, DoubleCritic
from torch_actor_critic_tpu.sac import SAC
from torch_actor_critic_tpu.serve import (
    BreakerOpenError,
    CircuitBreaker,
    EngineFleet,
    FleetRouter,
    MicroBatcher,
    ModelRegistry,
    PolicyClient,
    PolicyServer,
    ServeMetrics,
    ShedError,
    aggregate_snapshots,
)
from torch_actor_critic_tpu.telemetry.histogram import FixedBucketHistogram
from torch_actor_critic_tpu.telemetry.traceview import (
    RequestSpanLog,
    router_hop_events,
)
from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
from torch_actor_critic_tpu.utils.config import SACConfig

OBS_DIM, ACT_DIM = 17, 6
OBS = np.ones((OBS_DIM,), np.float32)


def make_actor_and_params(seed=0):
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32))
    params = actor.init(
        jax.random.key(seed), jnp.zeros((OBS_DIM,)), jax.random.key(1)
    )
    return actor, params


def flat_spec():
    return jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32)


def make_registry(breaker=None):
    actor, params = make_actor_and_params()
    reg = ModelRegistry()
    reg.register(
        "default", actor, flat_spec(), params=params, max_batch=4,
        warmup=False, breaker=breaker,
    )
    return reg, actor, params


def stall_replica(fleet, index, slot="default"):
    """Replace one replica's engine.act with an Event-gated version;
    returns (release_event, calls_list)."""
    engine, _, _ = fleet._replicas[index].registry.acquire(slot)
    release = threading.Event()
    calls = []
    real_act = engine.act

    def stalled(*args, **kwargs):
        calls.append(kwargs.get("deterministic", True))
        release.wait(30.0)
        return real_act(*args, **kwargs)

    engine.act = stalled
    return release, calls


def wait_until(pred, timeout=30.0, msg="condition never held"):
    deadline = time.time() + timeout
    while not pred():
        assert time.time() < deadline, msg
        time.sleep(0.002)


# ------------------------------------------------- engine-per-device fleet


class _FakeLoadBatcher:
    """Routing-policy stand-in: controlled load/EMA, records submits."""

    def __init__(self, load=0, ema=None):
        self._load = load
        self._ema = ema
        self.submits = 0
        self.mode = "continuous"

    def load_rows(self):
        return self._load

    @property
    def ema_row_s(self):
        return self._ema

    def queue_depth(self):
        return 0

    def submit(self, *a, **k):
        from concurrent.futures import Future

        self.submits += 1
        f = Future()
        f.set_result(None)
        return f

    def close(self, timeout=10.0):
        pass


def _fake_fleet(loads_emas):
    """EngineFleet with the real routing logic over fake batchers."""
    reg, _, _ = make_registry()
    fleet = EngineFleet(
        reg, devices=jax.local_devices()[:len(loads_emas)], max_batch=4,
    )
    fakes = []
    for rep, (load, ema) in zip(fleet._replicas, loads_emas):
        rep.batcher.close()
        rep.batcher = _FakeLoadBatcher(load, ema)
        fakes.append(rep.batcher)
    return reg, fleet, fakes


def test_least_loaded_scoring_is_load_times_ema():
    """The dispatcher minimizes estimated seconds-to-clear = load_rows
    x seconds-per-row EMA — depth alone is NOT the signal: a deep
    queue on a fast replica beats a shallow one on a slow replica."""
    reg, fleet, fakes = _fake_fleet(
        [(8, 0.001), (2, 0.1)]  # r0: 8ms to clear; r1: 200ms
    )
    try:
        for _ in range(3):
            fleet.submit(OBS)
        assert fakes[0].submits == 3  # fast replica wins despite depth
        assert fakes[1].submits == 0
    finally:
        fleet.close()
        reg.close()


def test_least_loaded_unmeasured_backlog_yields_and_idle_ties_spread():
    """An unmeasured replica WITH backlog (its first group never came
    back) is scored pessimistically and yields; an idle fleet spreads
    round-robin (all scores 0)."""
    reg, fleet, fakes = _fake_fleet([(1, None), (3, 0.001)])
    try:
        fleet.submit(OBS)
        assert fakes[1].submits == 1  # 3 rows x 1ms << 1 row x default
    finally:
        fleet.close()
        reg.close()
    reg2, fleet2, fakes2 = _fake_fleet([(0, None), (0, None), (0, None)])
    try:
        for _ in range(6):
            fleet2.submit(OBS)
        assert [f.submits for f in fakes2] == [2, 2, 2]  # round-robin
    finally:
        fleet2.close()
        reg2.close()


def test_stalled_replica_traffic_flows_to_free_replica():
    """End-to-end: with replica 0 wedged inside its engine (in-flight
    rows held, service rate unmeasured), subsequent requests are
    served by replica 1 while the wedge holds."""
    reg, _, _ = make_registry()
    with EngineFleet(
        reg, devices=jax.local_devices()[:2], max_batch=4, capacity=64,
    ) as fleet:
        release, _ = stall_replica(fleet, 0)
        try:
            # Round-robin from an idle fleet: the first request lands
            # on replica 0 and wedges there.
            blocked = fleet.submit(OBS)
            assert fleet._replicas[0].dispatched == 1
            wait_until(
                lambda: fleet._replicas[0].batcher.load_rows() == 1
                and fleet._replicas[0].batcher.queue_depth() == 0,
                msg="replica 0 never collected its request",
            )
            # Sequential blocking acts: replica 0 scores 1 row x the
            # pessimistic unmeasured rate; replica 1 is idle (score 0)
            # at each submit, so every act MUST route to replica 1.
            for _ in range(5):
                assert fleet.act(
                    OBS, timeout=30.0
                ).action.shape == (ACT_DIM,)
            assert fleet._replicas[0].dispatched == 1
            assert fleet._replicas[1].dispatched == 5
            assert fleet._replicas[1].batcher.ema_row_s is not None
            release.set()
            assert blocked.result(timeout=30.0).action.shape == (ACT_DIM,)
        finally:
            release.set()
    reg.close()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_breaker_open_replica_ejected_then_readmitted():
    """A replica whose breaker trips leaves the rotation (health gate);
    traffic continues on the others; after cooldown the half-open
    probe re-admits it. Every replica open => fleet-level 503."""
    clock = FakeClock()
    base_breaker = CircuitBreaker(
        fail_threshold=1, cooldown_s=10.0, clock=clock
    )
    reg, _, _ = make_registry(breaker=base_breaker)
    with EngineFleet(
        reg, devices=jax.local_devices()[:2], max_batch=4, capacity=64,
    ) as fleet:
        # Replica breakers inherit thresholds + the fake clock.
        br0 = fleet._replicas[0].registry.breaker("default")
        br1 = fleet._replicas[1].registry.breaker("default")
        assert br0.fail_threshold == 1 and br0._clock is clock

        br0.record_failure(RuntimeError("injected device fault"))
        assert br0.state == "open"
        futures = [fleet.submit(OBS) for _ in range(4)]
        assert fleet._replicas[0].dispatched == 0  # ejected
        assert fleet._replicas[1].dispatched == 4
        for f in futures:
            assert f.result(timeout=30.0).action.shape == (ACT_DIM,)

        # Whole fleet tripped: structured fleet-level shed.
        br1.record_failure(RuntimeError("injected device fault"))
        with pytest.raises(BreakerOpenError) as e:
            fleet.submit(OBS)
        assert e.value.reason == "breaker_open"
        assert fleet.metrics.snapshot()["shed_by_reason"]["breaker_open"] == 1

        # Cooldown -> half-open admits; healthy forwards close both.
        clock.advance(10.0)
        assert fleet.act(OBS, timeout=30.0).action.shape == (ACT_DIM,)
        assert fleet.act(OBS, timeout=30.0).action.shape == (ACT_DIM,)
        wait_until(
            lambda: br0.state == "closed" and br1.state == "closed",
            msg="probes never closed the replica breakers",
        )
        # replica breaker events landed in the shared registry log,
        # tagged with the replica index
        evs = [e for e in reg.breaker_events() if "replica" in e]
        assert any(e["event"] == "breaker_open" for e in evs)
    reg.close()


def test_fleet_shared_admission_bound_and_generation_propagation():
    """The capacity bound applies to the SUM of replica queues, and a
    hot-reload swap in the shared registry reaches every replica via
    generation-keyed placement."""
    reg, actor, params = make_registry()
    with EngineFleet(
        reg, devices=jax.local_devices()[:2], max_batch=4, capacity=4,
    ) as fleet:
        rel0, _ = stall_replica(fleet, 0)
        rel1, _ = stall_replica(fleet, 1)
        try:
            blockers = [fleet.submit(OBS) for _ in range(2)]
            wait_until(lambda: fleet.queue_depth() == 0)
            queued = [fleet.submit(OBS) for _ in range(4)]  # at bound
            with pytest.raises(ShedError) as e:
                fleet.submit(OBS)
            assert e.value.reason == "queue_full"
            assert e.value.detail["capacity"] == 4
            rel0.set()
            rel1.set()
            for f in blockers + queued:
                assert f.result(timeout=30.0).generation == 0
        finally:
            rel0.set()
            rel1.set()
        # swap propagates: both replicas serve the new generation
        gen = reg.swap("default", params)
        assert gen == 1
        for _ in range(2):  # round-robin covers both replicas
            assert fleet.act(OBS, timeout=30.0).generation == 1
    reg.close()


# ----------------------------------------------------- continuous batching


def test_continuous_admit_mid_formation_bitwise_matches_group_mode():
    """The same request mix answered in continuous and group modes is
    bitwise identical (engine row-wise invariance makes grouping
    invisible), including requests admitted while a group was already
    forming behind a stalled engine."""
    reg, _, _ = make_registry()
    rng = np.random.default_rng(3)
    singles = rng.standard_normal((6, OBS_DIM)).astype(np.float32)
    batch = rng.standard_normal((3, OBS_DIM)).astype(np.float32)

    results = {}
    for mode in ("group", "continuous"):
        with MicroBatcher(
            reg, max_batch=4, max_wait_ms=1.0, mode=mode,
            metrics=ServeMetrics(),
        ) as mb:
            engine, _, _ = reg.acquire("default")
            release = threading.Event()
            real_act = engine.act

            def stalled(*args, **kwargs):
                release.wait(30.0)
                return real_act(*args, **kwargs)

            engine.act = stalled
            try:
                futures = [mb.submit(singles[0])]
                wait_until(lambda: mb.queue_depth() == 0)
                # admitted mid-formation, while the engine is busy
                futures += [mb.submit(o) for o in singles[1:]]
                futures.append(mb.submit(batch))
                release.set()
                results[mode] = [
                    np.asarray(f.result(timeout=30.0).action)
                    for f in futures
                ]
            finally:
                release.set()
                engine.act = real_act
    assert len(results["group"]) == len(results["continuous"]) == 7
    for g, c in zip(results["group"], results["continuous"]):
        np.testing.assert_array_equal(g, c)


def test_continuous_deadline_priority_preempts_batch_filling():
    """With requests of two classes queued behind a busy engine, the
    continuous collector serves the class holding the nearest-deadline
    request first — deadline metadata preempts FIFO."""
    reg, _, _ = make_registry()
    with MicroBatcher(
        reg, max_batch=4, max_wait_ms=50.0, mode="continuous",
        metrics=ServeMetrics(), seed=7,
    ) as mb:
        engine, _, _ = reg.acquire("default")
        release = threading.Event()
        order = []
        real_act = engine.act

        def logged(*args, **kwargs):
            order.append(bool(kwargs.get("deterministic", True)))
            release.wait(30.0)
            return real_act(*args, **kwargs)

        engine.act = logged
        try:
            blocker = mb.submit(OBS, deterministic=True)
            wait_until(lambda: len(order) == 1)
            # FIFO would serve the deadline-free deterministic request
            # next; priority must pick the sampled class (deadline).
            free = mb.submit(OBS, deterministic=True)
            urgent = mb.submit(OBS, deterministic=False, deadline_s=20.0)
            release.set()
            for f in (blocker, free, urgent):
                assert f.result(timeout=30.0).action.shape == (ACT_DIM,)
            assert order[1] is False, (
                f"deadline-carrying class was not served first: {order}"
            )
        finally:
            release.set()
            engine.act = real_act
    reg.close()


def test_continuous_mode_is_server_default_and_group_pinned():
    """PolicyServer defaults to continuous; group mode stays available
    as the pinned compat path."""
    reg, _, _ = make_registry()
    with PolicyServer(reg, port=0, max_batch=4) as srv:
        assert srv.batcher.mode == "continuous"
    reg2, _, _ = make_registry()
    with PolicyServer(reg2, port=0, max_batch=4, mode="group") as srv:
        assert srv.batcher.mode == "group"
        srv.start()
        assert srv.client.act(OBS).action.shape == (ACT_DIM,)
    with pytest.raises(ValueError, match="mode"):
        MicroBatcher(reg2, max_batch=4, mode="rolling")


# ------------------------------------------------------------ fleet router


def _save_checkpoint(ckpt_dir, epoch, seed):
    cfg = SACConfig(hidden_sizes=(32, 32))
    sac = SAC(
        cfg,
        Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32)),
        DoubleCritic(hidden_sizes=(32, 32)),
        ACT_DIM,
    )
    state = sac.init_state(jax.random.key(seed), jnp.zeros((OBS_DIM,)))
    ck = Checkpointer(ckpt_dir, save_buffer=False)
    try:
        ck.save(epoch, state, extra={"config": cfg.to_json()}, wait=True)
    finally:
        ck.close()
    return state.actor_params


def _worker(params=None, ckpt_dir=None, span_log=None):
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32))
    reg = ModelRegistry()
    reg.register(
        "default", actor, flat_spec(), params=params,
        ckpt_dir=ckpt_dir, max_batch=4, warmup=False,
    )
    srv = PolicyServer(
        reg, port=0, max_batch=4, max_wait_ms=1.0, span_log=span_log,
    )
    srv.start()
    return srv


def test_router_routes_ejects_killed_worker_and_failover_zero_drops():
    """Kill a worker mid-rotation: the in-flight proxy attempt fails
    over to a healthy worker (the client sees a normal 200), the dead
    worker is ejected on the spot, and /healthz reflects it."""
    _, params = make_actor_and_params()
    w0, w1 = _worker(params=params), _worker(params=params)
    router = FleetRouter(
        [w0.address, w1.address], poll_interval_s=30.0,  # manual polls
    )
    router.poll_once()
    router.start()
    try:
        client = PolicyClient(url=router.address, retries=2)
        for _ in range(4):
            assert client.act(OBS, timeout=30.0).action.shape == (ACT_DIM,)
        view = router.membership()
        assert view["admitted_workers"] == 2
        assert {w["routed_total"] for w in view["workers"].values()} == {2}

        w0.close()  # the kill: connection refused from here on
        for _ in range(4):  # every request still answered
            assert client.act(OBS, timeout=30.0).action.shape == (ACT_DIM,)
        view = router.membership()
        assert view["workers"]["w0"]["admitted"] is False
        assert view["workers"]["w0"]["reason"] == "unreachable"
        assert router.failovers_total >= 1

        # router /healthz still 200 with one admitted worker
        health = json.loads(
            urlreq.urlopen(router.address + "/healthz", timeout=30).read()
        )
        assert health["status"] == "ok"
        assert health["admitted_workers"] == 1
    finally:
        router.close()
        w1.close()


def test_router_hop_tags_stitch_router_and_worker_spans():
    """The router appends a `>worker` hop tag to X-Request-Id; the
    worker records the tagged id in its span log and echoes it, so
    router hop spans and worker request spans share the base id."""
    _, params = make_actor_and_params()
    worker_log = RequestSpanLog()
    w0 = _worker(params=params, span_log=worker_log)
    router_log = RequestSpanLog()
    router = FleetRouter(
        [w0.address], poll_interval_s=30.0, span_log=router_log,
    )
    router.poll_once()
    router.start()
    try:
        req = urlreq.Request(
            router.address + "/act",
            data=json.dumps({"obs": OBS.tolist()}).encode(),
            headers={
                "Content-Type": "application/json",
                "X-Request-Id": "trace-me",
            },
        )
        with urlreq.urlopen(req, timeout=30) as resp:
            assert resp.headers["X-Request-Id"] == "trace-me>w0"
            body = json.loads(resp.read())
        assert len(body["action"]) == ACT_DIM
        # router side: base id + worker attribution
        recs = router_log.records()
        assert recs and recs[-1]["request_id"] == "trace-me"
        assert recs[-1]["worker"] == "w0"
        assert recs[-1]["outcome"] == "ok"
        # worker side: the hop-tagged id went through the batcher
        wait_until(lambda: len(worker_log) >= 1)
        wrecs = worker_log.records()
        assert wrecs[-1]["request_id"] == "trace-me>w0"
        # Perfetto events: one B/E pair on the router pid
        events = router_hop_events(recs)
        assert [e["ph"] for e in events] == ["B", "E"]
        assert events[0]["name"] == "hop w0"
        assert events[0]["args"]["request_id"] == "trace-me"
    finally:
        router.close()
        w0.close()


def test_rolling_reload_zero_dropped_requests(tmp_path):
    """Rolling reload across a 2-worker fleet under concurrent load:
    one worker at a time is ejected, hot-reloaded (validated), and
    re-admitted — every client request during the roll is answered
    and both workers end on the new epoch."""
    dirs = [tmp_path / "a", tmp_path / "b"]
    for i, d in enumerate(dirs):
        _save_checkpoint(d, 0, seed=i)
    workers = [_worker(ckpt_dir=str(d)) for d in dirs]
    router = FleetRouter(
        [w.address for w in workers], poll_interval_s=30.0,
    )
    router.poll_once()
    router.start()
    errors, answered = [], [0]
    stop = threading.Event()

    def load_loop():
        client = PolicyClient(url=router.address, retries=3)
        while not stop.is_set():
            try:
                res = client.act(OBS, timeout=30.0)
                assert res.action.shape == (ACT_DIM,)
                answered[0] += 1
            except Exception as e:  # noqa: BLE001 — recorded, asserted
                errors.append(repr(e))
    try:
        # the trainer "writes" a newer epoch to both workers' dirs
        for i, d in enumerate(dirs):
            _save_checkpoint(d, 1, seed=10 + i)
        herd = [threading.Thread(target=load_loop) for _ in range(3)]
        for th in herd:
            th.start()
        wait_until(lambda: answered[0] >= 3)  # load is flowing
        out = router.rolling_reload(settle_timeout_s=30.0)
        stop.set()
        for th in herd:
            th.join(timeout=30.0)
        assert set(out) == {"w0", "w1"}
        for name, status in out.items():
            assert status["readmitted"] is True, (name, status)
            assert status["reload"]["default"]["status"] == "ok", status
            assert status["reload"]["default"]["epoch"] == 1
        assert errors == [], errors[:3]
        assert answered[0] >= 3
        view = router.membership()
        assert view["admitted_workers"] == 2
        for w in workers:  # both serve generation 1 now
            health = json.loads(
                urlreq.urlopen(w.address + "/healthz", timeout=30).read()
            )
            assert health["slots"]["default"]["generation"] == 1
            assert health["slots"]["default"]["epoch"] == 1
    finally:
        stop.set()
        router.close()
        for w in workers:
            w.close()


# ------------------------------------------------------- /metrics merging


def test_aggregate_snapshots_matches_single_process_reference():
    """Fleet histogram merge == the histogram one process would have
    built from all samples: identical counts and percentiles. Counters
    sum; per-worker labels carry each worker's own rate and sheds."""
    rng = np.random.default_rng(0)
    lat_a = rng.uniform(0.5, 20.0, size=400)
    lat_b = rng.uniform(5.0, 300.0, size=300)
    ma, mb_, ref = ServeMetrics(), ServeMetrics(), FixedBucketHistogram()
    for v in lat_a:
        ma.record_done(float(v))
        ref.record(float(v))
    for v in lat_b:
        mb_.record_done(float(v))
        ref.record(float(v))
    ma.record_shed("queue_full")
    mb_.record_shed("queue_full")
    mb_.record_shed("breaker_open")
    snap_a, snap_b = ma.snapshot(), mb_.snapshot()
    agg = aggregate_snapshots({"w0": snap_a, "w1": snap_b, "w2": None})

    assert agg["responses_total"] == 700
    assert agg["sheds_total"] == 3
    assert agg["shed_by_reason"] == {"queue_full": 2, "breaker_open": 1}
    assert agg["workers_reporting"] == 2
    assert agg["workers"]["w2"] == {"unreachable": True}
    # per-worker labels: each worker's own counters survive unfolded
    assert agg["workers"]["w0"]["responses_total"] == 400
    assert agg["workers"]["w1"]["responses_total"] == 300
    assert agg["workers"]["w1"]["shed_by_reason"]["breaker_open"] == 1
    # merged histogram == single-process reference, bit for bit
    assert agg["latency_hist"]["counts"] == ref.raw_counts()["counts"]
    p50, p95, p99 = ref.percentiles((50, 95, 99))
    assert agg["p50_ms"] == round(p50, 3)
    assert agg["p95_ms"] == round(p95, 3)
    assert agg["p99_ms"] == round(p99, 3)
    assert agg["mean_ms"] == round(ref.mean, 3)
    assert agg["max_ms"] == round(ref.max, 3)
    # rates of disjoint streams add
    assert agg["requests_per_sec"] == round(
        snap_a["requests_per_sec"] + snap_b["requests_per_sec"], 2
    )


def test_aggregate_snapshots_restart_never_double_counts():
    """A worker that restarted reports reset counters; summing live
    values keeps the fleet total equal to what the processes hold."""
    m = ServeMetrics()
    for _ in range(5):
        m.record_done(1.0)
    before = aggregate_snapshots({"w0": m.snapshot()})
    assert before["responses_total"] == 5
    fresh = ServeMetrics()  # the restart
    fresh.record_done(1.0)
    after = aggregate_snapshots({"w0": fresh.snapshot()})
    assert after["responses_total"] == 1  # not 6: no double count
    assert after["workers"]["w0"]["responses_total"] == 1


def test_histogram_merge_raw_validates_spec():
    h = FixedBucketHistogram()
    other = FixedBucketHistogram(lo=1.0, hi=10.0, growth=2.0)
    with pytest.raises(ValueError, match="spec mismatch"):
        h.merge_raw(other.raw_counts())


# ------------------------------------------------------- HTTP client retry


def test_http_client_retries_honor_retry_after_with_jitter():
    """The HTTP PolicyClient backs off per the server's Retry-After
    (plus jitter), retries within its budget, and succeeds once the
    server recovers."""
    _, params = make_actor_and_params()
    w = _worker(params=params)
    sleeps = []

    class SeqRandom:
        def random(self):
            return 1.0  # deterministic max jitter: delay = 1.25 * base

    try:
        w.drain(flush_timeout_s=5.0)  # worker now sheds 503 draining

        client = PolicyClient(
            url=w.address, retries=2, backoff_s=0.05,
            sleep=sleeps.append, rng=SeqRandom(),
        )
        with pytest.raises(ShedError) as e:
            client.act(OBS, timeout=30.0)
        assert e.value.reason == "draining"
        # two retries, both honoring the server's Retry-After: 1s
        # (> the exponential base), times the 1.25 jitter factor
        assert sleeps == [1.25, 1.25]
        assert client.retries_total == 2
    finally:
        w.close()


def test_http_client_never_retries_past_deadline():
    """Deadline-aware: when Retry-After cannot fit inside the caller's
    remaining budget, the client raises immediately instead of
    sleeping through the deadline."""
    _, params = make_actor_and_params()
    w = _worker(params=params)
    sleeps = []
    try:
        # 4xx is never retried (checked pre-drain: draining answers
        # 503 for every POST /act regardless of slot)
        client2 = PolicyClient(url=w.address, retries=3)
        with pytest.raises(RuntimeError, match="HTTP 404"):
            client2.act(OBS, slot="nope", timeout=5.0)

        w.drain(flush_timeout_s=5.0)
        client = PolicyClient(
            url=w.address, retries=5, backoff_s=0.05, sleep=sleeps.append,
        )
        t0 = time.perf_counter()
        with pytest.raises(ShedError) as e:
            client.act(OBS, timeout=0.5)  # Retry-After=1 cannot fit
        assert e.value.reason == "draining"
        assert sleeps == []  # no blind sleep into the deadline
        assert time.perf_counter() - t0 < 5.0
    finally:
        w.close()


def test_http_client_requires_exactly_one_mode():
    reg, _, _ = make_registry()
    with pytest.raises(ValueError, match="either"):
        PolicyClient()
    with MicroBatcher(reg, max_batch=4) as mb:
        with pytest.raises(ValueError, match="either"):
            PolicyClient(reg, mb, url="http://x")
        with pytest.raises(RuntimeError, match="in-process"):
            PolicyClient(url="http://127.0.0.1:1").act_async(OBS)
    reg.close()
