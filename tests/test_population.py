"""Population training (parallel/population.py).

The correctness contract is INDEPENDENCE: a population of N must be
N single-learner runs stacked — same per-member numerics as running
each member alone with its member key, no cross-member leakage through
replay sampling, optimizer state, or PRNG streams. The reference can
only express this as N separate processes (ref ``sac/mpi.py:10-34``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.buffer.replay import init_replay_buffer
from torch_actor_critic_tpu.core.types import Batch
from torch_actor_critic_tpu.models import Actor, DoubleCritic
from torch_actor_critic_tpu.parallel import PopulationLearner, make_mesh
from torch_actor_critic_tpu.sac.algorithm import SAC
from torch_actor_critic_tpu.sac.trainer import Trainer
from torch_actor_critic_tpu.utils.config import SACConfig

OBS, ACT = 4, 2


def _learner(**over):
    cfg = SACConfig(
        hidden_sizes=(16, 16), batch_size=8, update_every=5,
        buffer_size=64, **over,
    )
    actor = Actor(act_dim=ACT, hidden_sizes=cfg.hidden_sizes, act_limit=1.0)
    critic = DoubleCritic(hidden_sizes=cfg.hidden_sizes)
    return SAC(cfg, actor, critic, ACT)


def _chunk(key, n_members, window=5):
    ks = jax.random.split(key, 5)
    shp = (n_members, window)
    return Batch(
        states=jax.random.normal(ks[0], shp + (OBS,)),
        actions=jax.random.uniform(ks[1], shp + (ACT,), minval=-1, maxval=1),
        rewards=jax.random.normal(ks[2], shp),
        next_states=jax.random.normal(ks[3], shp + (OBS,)),
        done=jnp.zeros(shp),
    )


def test_population_matches_stacked_single_runs():
    """Member i of a population burst == a lone learner run with member
    key i (tight-tolerance: vmap batches the matmuls, so low-bit
    float drift is allowed; trajectories must agree to ~1e-5)."""
    sac = _learner()
    pop = PopulationLearner(sac, 2)
    root = jax.random.key(0)
    example_obs = jnp.zeros((OBS,))

    state = pop.init_state(root, example_obs)
    buffer = pop.init_buffer(64, jax.ShapeDtypeStruct((OBS,), jnp.float32), ACT)
    chunk = _chunk(jax.random.key(1), 2)
    state, buffer, metrics = pop.update_burst(state, buffer, chunk, 3)

    # The same trajectory, one member at a time, through the plain
    # single-learner burst.
    member_keys = jax.random.split(root, 2)
    for i in range(2):
        st = sac.init_state(member_keys[i], example_obs)
        buf = init_replay_buffer(64, jax.ShapeDtypeStruct((OBS,), jnp.float32), ACT)
        ch = jax.tree_util.tree_map(lambda x: x[i], chunk)
        st, buf, m = sac.update_burst(st, buf, ch, 3)
        got = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda x: x[i], state.actor_params)
        )
        want = jax.tree_util.tree_leaves(st.actor_params)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(
            metrics["loss_q"][i], m["loss_q"], rtol=2e-5, atol=2e-6
        )
        # Replay rings advanced identically.
        assert int(buffer.size[i]) == int(buf.size)


def test_members_are_decorrelated():
    """Different member keys -> different inits and different sampled
    batches: after a burst the member params must differ."""
    sac = _learner()
    pop = PopulationLearner(sac, 3)
    state = pop.init_state(jax.random.key(7), jnp.zeros((OBS,)))
    leaves = jax.tree_util.tree_leaves(state.actor_params)
    assert not np.allclose(np.asarray(leaves[0][0]), np.asarray(leaves[0][1]))


def test_population_sharded_over_dp_mesh():
    """Member axis shards over dp with no collectives: 4 members on a
    dp=4 mesh — burst runs and outputs keep the member-axis sharding."""
    sac = _learner()
    mesh = make_mesh(dp=4)
    pop = PopulationLearner(sac, 4, mesh)
    state = pop.init_state(jax.random.key(0), jnp.zeros((OBS,)))
    buffer = pop.init_buffer(64, jax.ShapeDtypeStruct((OBS,), jnp.float32), ACT)
    chunk = pop.place_chunk(_chunk(jax.random.key(1), 4))
    state, buffer, metrics = pop.update_burst(state, buffer, chunk, 2)
    assert metrics["loss_q"].shape == (4,)
    assert np.all(np.isfinite(np.asarray(metrics["loss_q"])))
    # The ring stayed member-sharded over the mesh.
    assert len(buffer.data.rewards.sharding.device_set) == 4


def test_population_rejects_bad_geometry():
    sac = _learner()
    with pytest.raises(ValueError, match="divide evenly"):
        PopulationLearner(sac, 3, make_mesh(dp=2))
    with pytest.raises(ValueError, match="population must be >= 1"):
        SACConfig(population=0)
    # population x on_device is now the population-fused loop — a
    # valid combination (sac/ondevice.py PopulationOnDeviceLoop).
    SACConfig(population=2, on_device=True)
    # PBT knob validation.
    with pytest.raises(ValueError, match="population"):
        SACConfig(pbt_every=2)
    with pytest.raises(ValueError, match="on-device"):
        SACConfig(pbt_every=2, population=4)
    with pytest.raises(ValueError, match="pbt_quantile"):
        SACConfig(pbt_every=1, population=4, on_device=True,
                  pbt_quantile=0.75)
    with pytest.raises(ValueError, match="pbt_perturb"):
        SACConfig(pbt_every=1, population=4, on_device=True,
                  pbt_perturb=0.9)
    with pytest.raises(ValueError, match="pbt_ema"):
        SACConfig(pbt_every=1, population=4, on_device=True, pbt_ema=0.0)


def test_population_burst_cache_keyed_by_num_updates():
    """Alternating burst sizes must each keep their own compiled entry
    (the single-slot cache re-jitted EVERY call when sizes alternated)
    and dispatch under the train/population_burst watchdog scope."""
    from torch_actor_critic_tpu.diagnostics import get_watchdog

    sac = _learner()
    pop = PopulationLearner(sac, 2)
    state = pop.init_state(jax.random.key(0), jnp.zeros((OBS,)))
    buffer = pop.init_buffer(64, jax.ShapeDtypeStruct((OBS,), jnp.float32), ACT)
    wd = get_watchdog().install()

    def scope_compiles():
        return wd.snapshot()["by_source"].get("train/population_burst", 0)

    for i, n in enumerate((2, 3)):
        chunk = _chunk(jax.random.key(10 + i), 2)
        state, buffer, _ = pop.update_burst(state, buffer, chunk, n)
    assert sorted(pop._bursts) == [2, 3]
    assert scope_compiles() > 0  # dispatches attributed to the scope
    fn2, fn3 = pop._bursts[2], pop._bursts[3]
    steady = scope_compiles()
    for i, n in enumerate((2, 3, 2, 3)):
        chunk = _chunk(jax.random.key(50 + i), 2)
        state, buffer, _ = pop.update_burst(state, buffer, chunk, n)
    # cached callables reused, and NOT one recompile per alternation
    assert (pop._bursts[2], pop._bursts[3]) == (fn2, fn3)
    assert scope_compiles() == steady, wd.snapshot()["by_source"]


@pytest.fixture(scope="module")
def pop_trained(tmp_path_factory):
    cfg = SACConfig(
        population=3,
        hidden_sizes=(16, 16),
        batch_size=16,
        epochs=2,
        steps_per_epoch=40,
        start_steps=10,
        update_after=10,
        update_every=10,
        buffer_size=500,
        max_ep_len=100,
    )
    tr = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=1), seed=0)
    metrics = tr.train()
    yield tr, metrics
    tr.close()


def test_population_trainer_end_to_end(pop_trained):
    tr, metrics = pop_trained
    # One TrainState with a leading member axis, advanced in lockstep.
    # 80 lockstep steps, windows end at step 9,19,...,79; bursts run
    # once step > update_after(=10): 7 bursts x 10 updates.
    assert int(np.asarray(tr.state.step)[0]) == 70
    # N learning curves in the metrics.
    for i in range(3):
        assert f"reward_m{i}" in metrics
    # Aggregate grad-steps/s counts every member's updates.
    assert metrics["grad_steps_per_sec"] > 0
    # Members hold genuinely different policies (different init keys,
    # different exploration, different replay).
    leaves = jax.tree_util.tree_leaves(tr.state.actor_params)
    assert not np.allclose(np.asarray(leaves[0][0]), np.asarray(leaves[0][1]))


def test_population_eval_per_member(pop_trained):
    tr, _ = pop_trained
    ev = tr.evaluate(episodes=2, deterministic=True, seed=99)
    assert len(ev["per_member"]) == 3
    assert np.isfinite(ev["ep_ret_mean"])
    # Same protocol again -> same result (seeded, deterministic).
    ev2 = tr.evaluate(episodes=2, deterministic=True, seed=99)
    assert ev["ep_ret_mean"] == pytest.approx(ev2["ep_ret_mean"])


def test_population_composes_with_utd():
    """population x utd: N members each run round(update_every*utd)
    updates per window inside the one vmapped burst."""
    sac = _learner(utd=2.0)  # update_every=5 from _learner -> 10 updates
    pop = PopulationLearner(sac, 2)
    state = pop.init_state(jax.random.key(3), jnp.zeros((OBS,)))
    buffer = pop.init_buffer(64, jax.ShapeDtypeStruct((OBS,), jnp.float32), ACT)
    chunk = _chunk(jax.random.key(4), 2)
    state, buffer, m = pop.update_burst(
        state, buffer, chunk, sac.config.updates_per_window
    )
    assert int(np.asarray(state.step)[0]) == 10  # 5 steps x utd 2
    assert m["loss_q"].shape == (2,)
