"""Ring/sampling invariants for the device replay buffer.

The reference never tests its buffers (SURVEY.md §4 "Not tested");
these pin down the ring protocol the reference implements in
``buffer/replay_buffer.py:29-46``: pointer wraparound, size saturation,
oldest-overwrite, and sampling restricted to the valid region.
"""

import jax
import jax.numpy as jnp
import numpy as np

from torch_actor_critic_tpu.buffer import (
    init_replay_buffer,
    init_visual_replay_buffer,
    push,
    sample,
)
from torch_actor_critic_tpu.core.types import Batch

OBS_DIM, ACT_DIM, CAP = 4, 2, 10


def _chunk(start: int, n: int) -> Batch:
    """n transitions whose reward encodes their global index."""
    r = jnp.arange(start, start + n, dtype=jnp.float32)
    return Batch(
        states=jnp.tile(r[:, None], (1, OBS_DIM)),
        actions=jnp.zeros((n, ACT_DIM)),
        rewards=r,
        next_states=jnp.tile(r[:, None] + 0.5, (1, OBS_DIM)),
        done=jnp.zeros((n,)),
    )


def test_push_advances_ptr_and_size():
    buf = init_replay_buffer(CAP, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM)
    buf = push(buf, _chunk(0, 3))
    assert int(buf.ptr) == 3 and int(buf.size) == 3
    buf = push(buf, _chunk(3, 4))
    assert int(buf.ptr) == 7 and int(buf.size) == 7


def test_push_wraparound_overwrites_oldest():
    buf = init_replay_buffer(CAP, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM)
    buf = push(buf, _chunk(0, 8))
    buf = push(buf, _chunk(8, 6))  # wraps: slots 8,9,0,1,2,3
    assert int(buf.ptr) == 4
    assert int(buf.size) == CAP
    rewards = np.asarray(buf.data.rewards)
    # slots 0..3 hold transitions 10..13; slots 4..7 hold 4..7; 8,9 hold 8,9
    np.testing.assert_array_equal(rewards, [10, 11, 12, 13, 4, 5, 6, 7, 8, 9])


def test_sample_only_valid_region():
    buf = init_replay_buffer(CAP, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM)
    buf = push(buf, _chunk(0, 3))  # only rewards 0,1,2 valid
    batch = sample(buf, jax.random.key(0), 256)
    assert set(np.asarray(batch.rewards).tolist()) <= {0.0, 1.0, 2.0}
    # states/next_states must be gathered consistently with rewards
    np.testing.assert_array_equal(
        np.asarray(batch.states)[:, 0], np.asarray(batch.rewards)
    )
    np.testing.assert_array_equal(
        np.asarray(batch.next_states)[:, 0], np.asarray(batch.rewards) + 0.5
    )


def test_sample_covers_full_buffer():
    buf = init_replay_buffer(CAP, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM)
    buf = push(buf, _chunk(0, CAP))
    batch = sample(buf, jax.random.key(1), 1024)
    seen = set(np.asarray(batch.rewards).tolist())
    assert seen == set(float(i) for i in range(CAP))


def test_push_sample_jit_and_donate():
    """push must jit with buffer donation (the trainer's hot path)."""
    buf = init_replay_buffer(CAP, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM)
    push_jit = jax.jit(push, donate_argnums=(0,))
    buf = push_jit(buf, _chunk(0, 4))
    buf = push_jit(buf, _chunk(4, 4))
    assert int(buf.size) == 8
    batch = jax.jit(sample, static_argnums=(2,))(buf, jax.random.key(0), 16)
    assert batch.rewards.shape == (16,)


def test_visual_buffer_uint8_roundtrip():
    from torch_actor_critic_tpu.core.types import MultiObservation

    buf = init_visual_replay_buffer(CAP, feature_dim=3, frame_shape=(8, 8, 3), act_dim=2)
    assert buf.data.states.frame.dtype == jnp.uint8

    n = 4
    obs = MultiObservation(
        features=jnp.ones((n, 3)),
        frame=jnp.full((n, 8, 8, 3), 200, jnp.uint8),
    )
    chunk = Batch(
        states=obs,
        actions=jnp.zeros((n, 2)),
        rewards=jnp.arange(n, dtype=jnp.float32),
        next_states=obs,
        done=jnp.zeros((n,)),
    )
    buf = push(buf, chunk)
    batch = sample(buf, jax.random.key(0), 8)
    assert batch.states.frame.dtype == jnp.uint8
    assert int(batch.states.frame[0, 0, 0, 0]) == 200
    assert batch.states.features.shape == (8, 3)


def test_estimate_buffer_bytes():
    """Planning estimate behind the trainer's HBM-budget warning."""
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.buffer.replay import estimate_buffer_bytes
    from torch_actor_critic_tpu.core.types import MultiObservation

    flat = jax.ShapeDtypeStruct((17,), jnp.float32)
    # 2*17*4 (obs+next) + 6*4 (act) + 8 (reward+done) = 168 B/row
    assert estimate_buffer_bytes(1000, flat, 6) == 168_000

    vis = MultiObservation(
        features=jax.ShapeDtypeStruct((168,), jnp.float32),
        frame=jax.ShapeDtypeStruct((64, 64, 3), jnp.uint8),
    )
    per_row = 2 * (168 * 4 + 64 * 64 * 3) + 56 * 4 + 8
    assert estimate_buffer_bytes(10, vis, 56) == 10 * per_row
    # The motivating case: 1e6 visual transitions ~ 26 GB > any v5e.
    assert estimate_buffer_bytes(1_000_000, vis, 56) > 16 * 1024**3
