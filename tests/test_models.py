"""Shape and semantics tests for the MLP actor/critic stack.

Covers what the reference's ``tests/test_linear.py`` covers (shape
contracts for Actor/Critic/DoubleCritic) plus value-level properties
the reference never asserts: determinism flags, log-prob correctness
against an independent numerical computation, and action bounds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.models import Actor, Critic, DoubleCritic

OBS_DIM, ACT_DIM = 17, 6


@pytest.fixture
def actor_and_params():
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=(64, 64), act_limit=2.0)
    obs = jnp.zeros((OBS_DIM,))
    params = actor.init(jax.random.key(0), obs, jax.random.key(1))
    return actor, params


def test_actor_unbatched_shapes(actor_and_params):
    actor, params = actor_and_params
    obs = jax.random.normal(jax.random.key(2), (OBS_DIM,))
    action, logp = actor.apply(params, obs, jax.random.key(3))
    assert action.shape == (ACT_DIM,)
    assert logp.shape == ()


def test_actor_batched_shapes(actor_and_params):
    actor, params = actor_and_params
    obs = jax.random.normal(jax.random.key(2), (32, OBS_DIM))
    action, logp = actor.apply(params, obs, jax.random.key(3))
    assert action.shape == (32, ACT_DIM)
    assert logp.shape == (32,)


def test_actor_action_bounds(actor_and_params):
    actor, params = actor_and_params
    obs = 100.0 * jax.random.normal(jax.random.key(2), (128, OBS_DIM))
    action, _ = actor.apply(params, obs, jax.random.key(3))
    assert jnp.all(jnp.abs(action) <= 2.0)


def test_actor_deterministic_ignores_key(actor_and_params):
    actor, params = actor_and_params
    obs = jax.random.normal(jax.random.key(2), (4, OBS_DIM))
    a1, _ = actor.apply(params, obs, jax.random.key(3), deterministic=True)
    a2, _ = actor.apply(params, obs, jax.random.key(4), deterministic=True)
    np.testing.assert_array_equal(a1, a2)


def test_actor_without_logprob(actor_and_params):
    actor, params = actor_and_params
    obs = jnp.zeros((OBS_DIM,))
    _, logp = actor.apply(params, obs, jax.random.key(3), with_logprob=False)
    assert logp is None


def test_actor_logprob_matches_change_of_variables(actor_and_params):
    """logp(a) must equal the Gaussian density minus log|d tanh(u)/du|."""
    actor, params = actor_and_params
    obs = jax.random.normal(jax.random.key(2), (8, OBS_DIM))
    action, logp = actor.apply(params, obs, jax.random.key(3))
    # Recover u = atanh(a / act_limit) and recompute the correction the
    # direct (unstable-but-fine-here) way: sum log(1 - tanh(u)^2).
    u = jnp.arctanh(jnp.clip(action / 2.0, -1 + 1e-6, 1 - 1e-6))
    direct_correction = jnp.sum(jnp.log(1.0 - jnp.tanh(u) ** 2 + 1e-12), axis=-1)
    from torch_actor_critic_tpu.ops.distributions import tanh_log_prob_correction

    stable_correction = tanh_log_prob_correction(u)
    # fp32 atanh round-trip costs ~1e-3; this is a semantic check, not a
    # bit-exactness check.
    np.testing.assert_allclose(direct_correction, stable_correction, rtol=1e-2)


def test_critic_shapes():
    critic = Critic(hidden_sizes=(64, 64))
    obs = jnp.zeros((2, OBS_DIM))
    act = jnp.zeros((2, ACT_DIM))
    params = critic.init(jax.random.key(0), obs, act)
    q = critic.apply(params, obs, act)
    assert q.shape == (2,)


def test_double_critic_ensemble():
    critic = DoubleCritic(hidden_sizes=(64, 64), num_qs=2)
    obs = jnp.zeros((5, OBS_DIM))
    act = jnp.zeros((5, ACT_DIM))
    params = critic.init(jax.random.key(0), obs, act)
    q = critic.apply(params, obs, act)
    assert q.shape == (2, 5)
    # The two ensemble members must be independently initialized.
    assert not np.allclose(np.asarray(q[0]), np.asarray(q[1]))


def test_double_critic_matches_stacked_single_critics():
    """Ensemble member i must compute exactly a single Critic with its params."""
    critic = DoubleCritic(hidden_sizes=(32,), num_qs=2)
    obs = jax.random.normal(jax.random.key(1), (3, OBS_DIM))
    act = jax.random.normal(jax.random.key(2), (3, ACT_DIM))
    params = critic.init(jax.random.key(0), obs, act)
    q = critic.apply(params, obs, act)

    single = Critic(hidden_sizes=(32,))
    member0 = jax.tree_util.tree_map(lambda x: x[0], params)
    q0 = single.apply(
        {"params": member0["params"]["ensemble"]}, obs, act
    )
    np.testing.assert_allclose(np.asarray(q[0]), np.asarray(q0), rtol=1e-6)


class TestBfloat16Compute:
    """compute_dtype=bfloat16: matmuls in bf16, params/outputs float32.

    The torch reference has no mixed-precision path; this is the
    MXU-native extension (SACConfig.compute_dtype).
    """

    def test_params_stay_float32_and_outputs_are_float32(self):
        actor = Actor(act_dim=ACT_DIM, dtype=jnp.bfloat16)
        obs = jax.random.normal(jax.random.key(1), (4, OBS_DIM))
        params = actor.init(jax.random.key(0), obs, jax.random.key(2))
        for leaf in jax.tree_util.tree_leaves(params):
            assert leaf.dtype == jnp.float32, leaf.dtype
        action, logp = actor.apply(params, obs, jax.random.key(3))
        assert action.dtype == jnp.float32 and logp.dtype == jnp.float32

    def test_bf16_forward_close_to_f32(self):
        """Same params, bf16 vs f32 compute: outputs within bf16 noise."""
        f32 = DoubleCritic(hidden_sizes=(64, 64))
        bf16 = DoubleCritic(hidden_sizes=(64, 64), dtype=jnp.bfloat16)
        obs = jax.random.normal(jax.random.key(1), (8, OBS_DIM))
        act = jax.random.normal(jax.random.key(2), (8, ACT_DIM))
        params = f32.init(jax.random.key(0), obs, act)
        q32 = f32.apply(params, obs, act)
        q16 = bf16.apply(params, obs, act)
        assert q16.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(q16), np.asarray(q32), rtol=0.05, atol=0.05
        )

    def test_bf16_update_burst_trains(self):
        """A full fused burst in bf16 produces finite losses and f32 state."""
        from torch_actor_critic_tpu.buffer import init_replay_buffer, push
        from torch_actor_critic_tpu.core.types import Batch
        from torch_actor_critic_tpu.sac import SAC
        from torch_actor_critic_tpu.utils.config import SACConfig

        cfg = SACConfig(batch_size=16, hidden_sizes=(32, 32),
                        compute_dtype="bfloat16")
        sac = SAC(
            cfg,
            Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32), dtype=cfg.model_dtype),
            DoubleCritic(hidden_sizes=(32, 32), dtype=cfg.model_dtype),
            ACT_DIM,
        )
        state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
        buf = init_replay_buffer(
            500, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM
        )
        ks = jax.random.split(jax.random.key(1), 5)
        chunk = Batch(
            states=jax.random.normal(ks[0], (100, OBS_DIM)),
            actions=jnp.tanh(jax.random.normal(ks[1], (100, ACT_DIM))),
            rewards=jax.random.normal(ks[2], (100,)),
            next_states=jax.random.normal(ks[3], (100, OBS_DIM)),
            done=jnp.zeros((100,)),
        )
        buf = jax.jit(push, donate_argnums=(0,))(buf, chunk)
        state, buf, m = jax.jit(sac.update_burst, static_argnums=(3,))(
            state, buf, chunk, 5
        )
        assert bool(jnp.isfinite(m["loss_q"])) and bool(jnp.isfinite(m["loss_pi"]))
        for leaf in jax.tree_util.tree_leaves(state.actor_params):
            assert leaf.dtype == jnp.float32

    def test_config_validates_compute_dtype(self):
        from torch_actor_critic_tpu.utils.config import SACConfig

        with pytest.raises(ValueError):
            SACConfig(compute_dtype="float16")

    @pytest.mark.slow
    def test_bf16_sequence_and_visual_forward(self):
        from torch_actor_critic_tpu.core.types import MultiObservation
        from torch_actor_critic_tpu.models import SequenceActor, VisualActor

        seq = SequenceActor(act_dim=ACT_DIM, d_model=16, num_heads=2,
                            num_layers=1, max_len=8, dtype=jnp.bfloat16)
        h = jax.random.normal(jax.random.key(1), (2, 8, OBS_DIM))
        p = seq.init(jax.random.key(0), h, jax.random.key(2))
        a, lp = seq.apply(p, h, jax.random.key(3))
        assert a.dtype == jnp.float32 and bool(jnp.all(jnp.isfinite(lp)))

        vis = VisualActor(act_dim=ACT_DIM, hidden_sizes=(16,),
                          kernel_sizes=(3, 3, 3), strides=(2, 2, 1),
                          dtype=jnp.bfloat16)
        obs = MultiObservation(
            features=jax.random.normal(jax.random.key(4), (2, 5)),
            frame=jax.random.randint(
                jax.random.key(5), (2, 16, 16, 3), 0, 256, jnp.uint8
            ),
        )
        p = vis.init(jax.random.key(0), obs, jax.random.key(2))
        a, lp = vis.apply(p, obs, jax.random.key(3))
        assert a.dtype == jnp.float32 and bool(jnp.all(jnp.isfinite(lp)))
