"""Shape and semantics tests for the MLP actor/critic stack.

Covers what the reference's ``tests/test_linear.py`` covers (shape
contracts for Actor/Critic/DoubleCritic) plus value-level properties
the reference never asserts: determinism flags, log-prob correctness
against an independent numerical computation, and action bounds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.models import Actor, Critic, DoubleCritic

OBS_DIM, ACT_DIM = 17, 6


@pytest.fixture
def actor_and_params():
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=(64, 64), act_limit=2.0)
    obs = jnp.zeros((OBS_DIM,))
    params = actor.init(jax.random.key(0), obs, jax.random.key(1))
    return actor, params


def test_actor_unbatched_shapes(actor_and_params):
    actor, params = actor_and_params
    obs = jax.random.normal(jax.random.key(2), (OBS_DIM,))
    action, logp = actor.apply(params, obs, jax.random.key(3))
    assert action.shape == (ACT_DIM,)
    assert logp.shape == ()


def test_actor_batched_shapes(actor_and_params):
    actor, params = actor_and_params
    obs = jax.random.normal(jax.random.key(2), (32, OBS_DIM))
    action, logp = actor.apply(params, obs, jax.random.key(3))
    assert action.shape == (32, ACT_DIM)
    assert logp.shape == (32,)


def test_actor_action_bounds(actor_and_params):
    actor, params = actor_and_params
    obs = 100.0 * jax.random.normal(jax.random.key(2), (128, OBS_DIM))
    action, _ = actor.apply(params, obs, jax.random.key(3))
    assert jnp.all(jnp.abs(action) <= 2.0)


def test_actor_deterministic_ignores_key(actor_and_params):
    actor, params = actor_and_params
    obs = jax.random.normal(jax.random.key(2), (4, OBS_DIM))
    a1, _ = actor.apply(params, obs, jax.random.key(3), deterministic=True)
    a2, _ = actor.apply(params, obs, jax.random.key(4), deterministic=True)
    np.testing.assert_array_equal(a1, a2)


def test_actor_without_logprob(actor_and_params):
    actor, params = actor_and_params
    obs = jnp.zeros((OBS_DIM,))
    _, logp = actor.apply(params, obs, jax.random.key(3), with_logprob=False)
    assert logp is None


def test_actor_logprob_matches_change_of_variables(actor_and_params):
    """logp(a) must equal the Gaussian density minus log|d tanh(u)/du|."""
    actor, params = actor_and_params
    obs = jax.random.normal(jax.random.key(2), (8, OBS_DIM))
    action, logp = actor.apply(params, obs, jax.random.key(3))
    # Recover u = atanh(a / act_limit) and recompute the correction the
    # direct (unstable-but-fine-here) way: sum log(1 - tanh(u)^2).
    u = jnp.arctanh(jnp.clip(action / 2.0, -1 + 1e-6, 1 - 1e-6))
    direct_correction = jnp.sum(jnp.log(1.0 - jnp.tanh(u) ** 2 + 1e-12), axis=-1)
    from torch_actor_critic_tpu.ops.distributions import tanh_log_prob_correction

    stable_correction = tanh_log_prob_correction(u)
    # fp32 atanh round-trip costs ~1e-3; this is a semantic check, not a
    # bit-exactness check.
    np.testing.assert_allclose(direct_correction, stable_correction, rtol=1e-2)


def test_critic_shapes():
    critic = Critic(hidden_sizes=(64, 64))
    obs = jnp.zeros((2, OBS_DIM))
    act = jnp.zeros((2, ACT_DIM))
    params = critic.init(jax.random.key(0), obs, act)
    q = critic.apply(params, obs, act)
    assert q.shape == (2,)


def test_double_critic_ensemble():
    critic = DoubleCritic(hidden_sizes=(64, 64), num_qs=2)
    obs = jnp.zeros((5, OBS_DIM))
    act = jnp.zeros((5, ACT_DIM))
    params = critic.init(jax.random.key(0), obs, act)
    q = critic.apply(params, obs, act)
    assert q.shape == (2, 5)
    # The two ensemble members must be independently initialized.
    assert not np.allclose(np.asarray(q[0]), np.asarray(q[1]))


def test_double_critic_matches_stacked_single_critics():
    """Ensemble member i must compute exactly a single Critic with its params."""
    critic = DoubleCritic(hidden_sizes=(32,), num_qs=2)
    obs = jax.random.normal(jax.random.key(1), (3, OBS_DIM))
    act = jax.random.normal(jax.random.key(2), (3, ACT_DIM))
    params = critic.init(jax.random.key(0), obs, act)
    q = critic.apply(params, obs, act)

    single = Critic(hidden_sizes=(32,))
    member0 = jax.tree_util.tree_map(lambda x: x[0], params)
    q0 = single.apply(
        {"params": member0["params"]["ensemble"]}, obs, act
    )
    np.testing.assert_allclose(np.asarray(q[0]), np.asarray(q0), rtol=1e-6)
