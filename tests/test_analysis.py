"""tac-lint (torch_actor_critic_tpu/analysis): per-rule fixtures —
positive and negative per family — plus the whole-package clean-run
pin that wires the pass into tier-1, and the suppression policy
(every suppression must name a known rule).

Fixtures go through ``lint_sources`` (in-memory), the same engine
``python -m torch_actor_critic_tpu.analysis`` / ``make lint`` runs
over files.
"""

import pathlib
import textwrap

import torch_actor_critic_tpu
from torch_actor_critic_tpu.analysis import (
    ALL_RULES,
    RULE_FAMILIES,
    lint_paths,
    lint_sources,
)

REPO = pathlib.Path(torch_actor_critic_tpu.__file__).parent.parent
PKG = REPO / "torch_actor_critic_tpu"
SCRIPTS = REPO / "scripts"


def lint_one(src: str, path: str = "fixture.py", rules=None):
    return lint_sources({path: textwrap.dedent(src)}, rules=rules)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------- jit-hygiene


def test_host_sync_in_jit_item():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x.item()

        fwd_j = jax.jit(fwd)
    """)
    assert rules_of(findings) == ["host-sync-in-jit"]
    assert findings[0].line == 5


def test_host_sync_negative_outside_trace():
    # .item() in plain host code is fine — only traced code is held to
    # jit hygiene.
    findings = lint_one("""
        def report(x):
            return x.item()
    """)
    assert findings == []


def test_host_cast_on_traced_value_flagged_static_shape_not():
    findings = lint_one("""
        import jax
        import numpy as np

        def fwd(x):
            n = int(np.prod(x.shape))   # static under trace: fine
            return float(x)             # traced value: host sync

        fwd_j = jax.jit(fwd)
    """)
    assert rules_of(findings) == ["host-sync-in-jit"]
    assert len(findings) == 1
    assert findings[0].line == 7


def test_wallclock_in_jit():
    findings = lint_one("""
        import jax
        import time

        def step(x):
            return x * time.time()

        step_j = jax.jit(step)
    """)
    assert rules_of(findings) == ["wallclock-in-jit"]


def test_host_random_in_jit_jax_random_ok():
    findings = lint_one("""
        import jax
        import random

        def step(key, x):
            a, b = jax.random.split(key)   # traced-safe: never flagged
            return x + random.random()

        step_j = jax.jit(step)
    """)
    assert rules_of(findings) == ["host-random-in-jit"]


def test_reachability_through_scan_and_helpers():
    # The violation sits two hops from the jit boundary: jit -> scan
    # body -> helper. The reachability walk must still find it.
    findings = lint_one("""
        import jax
        import time

        def helper(c):
            return c * time.perf_counter()

        def body(c, x):
            return helper(c), x

        def epoch(c, xs):
            return jax.lax.scan(body, c, xs)

        epoch_j = jax.jit(epoch)
    """)
    assert rules_of(findings) == ["wallclock-in-jit"]


def test_frame_f32_materialize_astype_flagged():
    findings = lint_one("""
        import jax.numpy as jnp

        def stage(batch):
            return batch.states.frame.astype(jnp.float32)
    """)
    assert rules_of(findings) == ["frame-f32-materialize"]


def test_frame_f32_materialize_div255_flagged():
    findings = lint_one("""
        def decode(frames):
            return frames / 255.0
    """)
    assert rules_of(findings) == ["frame-f32-materialize"]


def test_frame_rule_negatives():
    # Non-frame casts, uint8 frame moves, and activation casts (a
    # name without 'frame') are all fine.
    findings = lint_one("""
        import jax.numpy as jnp

        def ok(batch, frames, x):
            a = batch.rewards.astype(jnp.float32)
            b = frames.astype(jnp.uint8)
            c = x / 255.0
            d = x.astype(jnp.float32)
            return a, b, c, d
    """)
    assert findings == []


def test_frame_decode_home_is_exempt():
    findings = lint_one(
        """
        import jax.numpy as jnp

        def _decode(frame):
            return frame.astype(jnp.float32) / 255.0
        """,
        path="torch_actor_critic_tpu/ops/pixels.py",
    )
    assert findings == []


def test_frame_decode_allowlist_scope_and_staleness():
    # The allowlisted SimpleCNN.__call__ decode passes; the same file
    # WITHOUT the decode trips stale-allowlist (checked, never
    # trusted — the shard-map precedent).
    allowed = lint_one(
        """
        import jax.numpy as jnp

        class SimpleCNN:
            def __call__(self, frame):
                return frame.astype(jnp.float32)
        """,
        path="torch_actor_critic_tpu/models/visual.py",
    )
    assert allowed == []
    stale = lint_one(
        "X = 1\n",
        path="torch_actor_critic_tpu/models/visual.py",
    )
    assert "stale-allowlist" in rules_of(stale)
    # An un-allowlisted scope in the same file is still flagged.
    elsewhere = lint_one(
        """
        import jax.numpy as jnp

        class SimpleCNN:
            def __call__(self, frame):
                return frame.astype(jnp.float32)

        def other(frames):
            return frames / 255.0
        """,
        path="torch_actor_critic_tpu/models/visual.py",
    )
    assert "frame-f32-materialize" in rules_of(elsewhere)


def test_stale_entry_point_reported_on_package_runs():
    # A "package" (root __init__ present) whose seed table files are
    # gone must fail loudly instead of the walk silently going blind.
    findings = lint_sources({
        "torch_actor_critic_tpu/__init__.py": "",
    })
    assert "stale-entry-point" in rules_of(findings)


# -------------------------------------------------------- recompile-risk


def test_jit_cache_discard():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x + 1

        def run(x):
            return jax.jit(fwd)(x)
    """)
    assert rules_of(findings) == ["jit-cache-discard"]


def test_jit_bound_then_called_is_clean():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x + 1

        fwd_j = jax.jit(fwd)

        def run(x):
            return fwd_j(x)
    """)
    assert findings == []


def test_jit_in_loop():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x + 1

        def run(xs):
            out = []
            for x in xs:
                f = jax.jit(fwd)
                out.append(f(x))
            return out
    """)
    assert "jit-in-loop" in rules_of(findings)


def test_varying_shape_arg():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x.sum()

        fwd_j = jax.jit(fwd)

        def run(x, n):
            return fwd_j(x[:n])
    """)
    assert rules_of(findings) == ["varying-shape-arg"]


def test_donated_reuse_flagged_rebind_clean():
    src = """
        import jax

        def push(buf, chunk):
            return buf

        push_j = jax.jit(push, donate_argnums=(0,))

        def bad(buf, chunk):
            out = push_j(buf, chunk)
            return buf, out           # buf's buffer may be aliased

        def good(buf, chunk):
            buf = push_j(buf, chunk)  # rebinding is the sound pattern
            return buf
    """
    findings = lint_one(src)
    assert rules_of(findings) == ["donated-reuse"]
    assert len(findings) == 1


def test_shard_map_hot_path_and_allowlist():
    bad = lint_one(
        """
        from jax.experimental.shard_map import shard_map

        def burst(f, mesh):
            return shard_map(f, mesh=mesh)
        """,
        path="mypkg/train.py",
    )
    assert "shard-map-hot-path" in rules_of(bad)
    # The rule's home files are exempt by definition.
    home = lint_one(
        "from jax.experimental.shard_map import shard_map\n",
        path="parallel/context.py",
    )
    assert home == []


def test_stale_allowlist_reported():
    # A file matching an allowlist entry but containing no shard_map
    # reference any more: the entry is dead and must be flagged.
    findings = lint_sources({"parallel/dp.py": "x = 1\n"})
    assert "stale-allowlist" in rules_of(findings)


# ------------------------------------------------------- lock-discipline


_LOCKED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock

        def add(self, x):
            {add_body}

        def drain(self):
            with self._lock:
                out, self._items = self._items, []
            return out
"""


def test_unlocked_guarded_access():
    findings = lint_one(
        _LOCKED_CLASS.format(add_body="self._items.append(x)")
    )
    assert rules_of(findings) == ["unlocked-guarded-access"]


def test_guarded_access_under_lock_clean():
    findings = lint_one(_LOCKED_CLASS.format(
        add_body="with self._lock:\n                self._items.append(x)"
    ))
    assert findings == []


def test_lock_holding_method_conventions():
    # _locked suffix and the "Callers hold self.<lock>" docstring both
    # mark a method as called under the lock.
    findings = lint_one("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: _lock

            def _bump_locked(self):
                self._n += 1

            def _peek(self):
                \"\"\"Callers hold ``self._lock``.\"\"\"
                return self._n

            def bump(self):
                with self._lock:
                    self._bump_locked()
                    return self._peek()
    """)
    assert findings == []


def test_condition_aliases_its_lock():
    findings = lint_one("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._nonempty = threading.Condition(self._lock)
                self._q = []  # guarded-by: _lock

            def put(self, x):
                with self._nonempty:
                    self._q.append(x)
    """)
    assert findings == []


def test_unguarded_shared_attr():
    findings = lint_one("""
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def on_request(self):
                self.count += 1

            def reset(self):
                self.count = 0
    """)
    assert rules_of(findings) == ["unguarded-shared-attr"]


def test_unknown_guard():
    findings = lint_one("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0  # guarded-by: _mutex

            def get(self):
                with self._lock:
                    return self._x
    """)
    assert "unknown-guard" in rules_of(findings)


# ---------------------------------------------------------- conventions


def test_silent_exception_swallow_outside_shutdown():
    findings = lint_one("""
        def handshake():
            try:
                risky()
            except Exception:
                pass
    """)
    assert rules_of(findings) == ["silent-exception-swallow"]


def test_swallow_allowed_on_shutdown_paths_and_narrow_types():
    findings = lint_one("""
        def close():
            try:
                flush()
            except Exception:
                pass

        def handshake():
            try:
                risky()
            except OSError:
                pass
    """)
    assert findings == []


def test_mutable_default_arg():
    findings = lint_one("""
        def f(xs=[]):
            return xs
    """)
    assert rules_of(findings) == ["mutable-default-arg"]


def test_suffix_reduction_mismatch():
    findings = lint_one("""
        import jax.numpy as jnp

        def metrics(x):
            return {
                "loss_max": jnp.min(x),   # contradicts the suffix
                "loss_min": jnp.min(x),   # coherent
                "steps_sum": jnp.sum(x),  # coherent
            }
    """)
    assert rules_of(findings) == ["suffix-reduction-mismatch"]
    assert len(findings) == 1


# ----------------------------------------------------------- suppression


def test_suppression_must_name_a_rule():
    findings = lint_one("""
        def f(xs=[]):  # tac-lint: disable
            return xs
    """)
    # The blanket suppression suppresses nothing AND is itself a
    # finding; the mutable default still reports.
    assert rules_of(findings) == ["bare-suppression", "mutable-default-arg"]


def test_suppression_naming_unknown_rule_is_a_finding():
    findings = lint_one("""
        def f(xs=[]):  # tac-lint: disable=definitely-not-a-rule
            return xs
    """)
    assert rules_of(findings) == ["bare-suppression", "mutable-default-arg"]


def test_named_suppression_suppresses_exactly_that_rule():
    findings = lint_one("""
        def f(xs=[]):  # tac-lint: disable=mutable-default-arg
            return xs
    """)
    assert findings == []


# --------------------------------------------------------- whole package


def test_whole_package_and_scripts_clean():
    """THE tier-1 wiring: a new violation anywhere in the package or
    scripts/ fails pytest. Suppression budget (docs/ANALYSIS.md): every
    remaining suppression names a rule (enforced by bare-suppression)
    and the total stays small."""
    findings = lint_paths([str(PKG), str(SCRIPTS)])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_suppression_budget():
    import re

    n = 0
    for f in list(PKG.rglob("*.py")) + list(SCRIPTS.rglob("*.py")):
        if "__pycache__" in f.parts:
            continue
        n += len(re.findall(r"tac-lint:\s*disable=", f.read_text()))
    assert n <= 10, (
        f"{n} tac-lint suppressions in the package/scripts — the "
        "budget is 10, each justified in docs/ANALYSIS.md"
    )


def test_rule_catalog_is_consistent():
    assert ALL_RULES == {
        r for rules in RULE_FAMILIES.values() for r in rules
    }
    # Every family contributes at least one rule and the families the
    # issue names are all present.
    for family in (
        "jit-hygiene", "recompile-risk", "lock-discipline", "conventions",
    ):
        assert RULE_FAMILIES[family]
