"""tac-lint (torch_actor_critic_tpu/analysis): per-rule fixtures —
positive and negative per family — plus the whole-package clean-run
pin that wires the pass into tier-1, and the suppression policy
(every suppression must name a known rule).

Fixtures go through ``lint_sources`` (in-memory), the same engine
``python -m torch_actor_critic_tpu.analysis`` / ``make lint`` runs
over files.
"""

import pathlib
import textwrap

import torch_actor_critic_tpu
from torch_actor_critic_tpu.analysis import (
    ALL_RULES,
    RULE_FAMILIES,
    lint_paths,
    lint_sources,
)

REPO = pathlib.Path(torch_actor_critic_tpu.__file__).parent.parent
PKG = REPO / "torch_actor_critic_tpu"
SCRIPTS = REPO / "scripts"


def lint_one(src: str, path: str = "fixture.py", rules=None):
    return lint_sources({path: textwrap.dedent(src)}, rules=rules)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------- jit-hygiene


def test_host_sync_in_jit_item():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x.item()

        fwd_j = jax.jit(fwd)
    """)
    assert rules_of(findings) == ["host-sync-in-jit"]
    assert findings[0].line == 5


def test_host_sync_negative_outside_trace():
    # .item() in plain host code is fine — only traced code is held to
    # jit hygiene.
    findings = lint_one("""
        def report(x):
            return x.item()
    """)
    assert findings == []


def test_host_cast_on_traced_value_flagged_static_shape_not():
    findings = lint_one("""
        import jax
        import numpy as np

        def fwd(x):
            n = int(np.prod(x.shape))   # static under trace: fine
            return float(x)             # traced value: host sync

        fwd_j = jax.jit(fwd)
    """)
    assert rules_of(findings) == ["host-sync-in-jit"]
    assert len(findings) == 1
    assert findings[0].line == 7


def test_wallclock_in_jit():
    findings = lint_one("""
        import jax
        import time

        def step(x):
            return x * time.time()

        step_j = jax.jit(step)
    """)
    assert rules_of(findings) == ["wallclock-in-jit"]


def test_host_random_in_jit_jax_random_ok():
    findings = lint_one("""
        import jax
        import random

        def step(key, x):
            a, b = jax.random.split(key)   # traced-safe: never flagged
            return x + random.random()

        step_j = jax.jit(step)
    """)
    assert rules_of(findings) == ["host-random-in-jit"]


def test_reachability_through_scan_and_helpers():
    # The violation sits two hops from the jit boundary: jit -> scan
    # body -> helper. The reachability walk must still find it.
    findings = lint_one("""
        import jax
        import time

        def helper(c):
            return c * time.perf_counter()

        def body(c, x):
            return helper(c), x

        def epoch(c, xs):
            return jax.lax.scan(body, c, xs)

        epoch_j = jax.jit(epoch)
    """)
    assert rules_of(findings) == ["wallclock-in-jit"]


def test_frame_f32_materialize_astype_flagged():
    findings = lint_one("""
        import jax.numpy as jnp

        def stage(batch):
            return batch.states.frame.astype(jnp.float32)
    """)
    assert rules_of(findings) == ["frame-f32-materialize"]


def test_frame_f32_materialize_div255_flagged():
    findings = lint_one("""
        def decode(frames):
            return frames / 255.0
    """)
    assert rules_of(findings) == ["frame-f32-materialize"]


def test_frame_rule_negatives():
    # Non-frame casts, uint8 frame moves, and activation casts (a
    # name without 'frame') are all fine.
    findings = lint_one("""
        import jax.numpy as jnp

        def ok(batch, frames, x):
            a = batch.rewards.astype(jnp.float32)
            b = frames.astype(jnp.uint8)
            c = x / 255.0
            d = x.astype(jnp.float32)
            return a, b, c, d
    """)
    assert findings == []


def test_frame_decode_home_is_exempt():
    findings = lint_one(
        """
        import jax.numpy as jnp

        def _decode(frame):
            return frame.astype(jnp.float32) / 255.0
        """,
        path="torch_actor_critic_tpu/ops/pixels.py",
    )
    assert findings == []


def test_frame_decode_allowlist_scope_and_staleness():
    # The allowlisted SimpleCNN.__call__ decode passes; the same file
    # WITHOUT the decode trips stale-allowlist (checked, never
    # trusted — the shard-map precedent).
    allowed = lint_one(
        """
        import jax.numpy as jnp

        class SimpleCNN:
            def __call__(self, frame):
                return frame.astype(jnp.float32)
        """,
        path="torch_actor_critic_tpu/models/visual.py",
    )
    assert allowed == []
    stale = lint_one(
        "X = 1\n",
        path="torch_actor_critic_tpu/models/visual.py",
    )
    assert "stale-allowlist" in rules_of(stale)
    # An un-allowlisted scope in the same file is still flagged.
    elsewhere = lint_one(
        """
        import jax.numpy as jnp

        class SimpleCNN:
            def __call__(self, frame):
                return frame.astype(jnp.float32)

        def other(frames):
            return frames / 255.0
        """,
        path="torch_actor_critic_tpu/models/visual.py",
    )
    assert "frame-f32-materialize" in rules_of(elsewhere)


def test_stale_entry_point_reported_on_package_runs():
    # A "package" (root __init__ present) whose seed table files are
    # gone must fail loudly instead of the walk silently going blind.
    findings = lint_sources({
        "torch_actor_critic_tpu/__init__.py": "",
    })
    assert "stale-entry-point" in rules_of(findings)


# -------------------------------------------------------- recompile-risk


def test_jit_cache_discard():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x + 1

        def run(x):
            return jax.jit(fwd)(x)
    """)
    assert rules_of(findings) == ["jit-cache-discard"]


def test_jit_bound_then_called_is_clean():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x + 1

        fwd_j = jax.jit(fwd)

        def run(x):
            return fwd_j(x)
    """)
    assert findings == []


def test_jit_in_loop():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x + 1

        def run(xs):
            out = []
            for x in xs:
                f = jax.jit(fwd)
                out.append(f(x))
            return out
    """)
    assert "jit-in-loop" in rules_of(findings)


def test_varying_shape_arg():
    findings = lint_one("""
        import jax

        def fwd(x):
            return x.sum()

        fwd_j = jax.jit(fwd)

        def run(x, n):
            return fwd_j(x[:n])
    """)
    assert rules_of(findings) == ["varying-shape-arg"]


def test_donated_reuse_flagged_rebind_clean():
    src = """
        import jax

        def push(buf, chunk):
            return buf

        push_j = jax.jit(push, donate_argnums=(0,))

        def bad(buf, chunk):
            out = push_j(buf, chunk)
            return buf, out           # buf's buffer may be aliased

        def good(buf, chunk):
            buf = push_j(buf, chunk)  # rebinding is the sound pattern
            return buf
    """
    findings = lint_one(src)
    assert rules_of(findings) == ["donated-reuse"]
    assert len(findings) == 1


def test_shard_map_hot_path_and_allowlist():
    bad = lint_one(
        """
        from jax.experimental.shard_map import shard_map

        def burst(f, mesh):
            return shard_map(f, mesh=mesh)
        """,
        path="mypkg/train.py",
    )
    assert "shard-map-hot-path" in rules_of(bad)
    # The rule's home files are exempt by definition.
    home = lint_one(
        "from jax.experimental.shard_map import shard_map\n",
        path="parallel/context.py",
    )
    assert home == []


def test_stale_allowlist_reported():
    # A file matching an allowlist entry but containing no shard_map
    # reference any more: the entry is dead and must be flagged.
    findings = lint_sources({"parallel/dp.py": "x = 1\n"})
    assert "stale-allowlist" in rules_of(findings)


# ------------------------------------------------------- lock-discipline


_LOCKED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock

        def add(self, x):
            {add_body}

        def drain(self):
            with self._lock:
                out, self._items = self._items, []
            return out
"""


def test_unlocked_guarded_access():
    findings = lint_one(
        _LOCKED_CLASS.format(add_body="self._items.append(x)")
    )
    assert rules_of(findings) == ["unlocked-guarded-access"]


def test_guarded_access_under_lock_clean():
    findings = lint_one(_LOCKED_CLASS.format(
        add_body="with self._lock:\n                self._items.append(x)"
    ))
    assert findings == []


def test_lock_holding_method_conventions():
    # _locked suffix and the "Callers hold self.<lock>" docstring both
    # mark a method as called under the lock.
    findings = lint_one("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: _lock

            def _bump_locked(self):
                self._n += 1

            def _peek(self):
                \"\"\"Callers hold ``self._lock``.\"\"\"
                return self._n

            def bump(self):
                with self._lock:
                    self._bump_locked()
                    return self._peek()
    """)
    assert findings == []


def test_condition_aliases_its_lock():
    findings = lint_one("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._nonempty = threading.Condition(self._lock)
                self._q = []  # guarded-by: _lock

            def put(self, x):
                with self._nonempty:
                    self._q.append(x)
    """)
    assert findings == []


def test_unguarded_shared_attr():
    findings = lint_one("""
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def on_request(self):
                self.count += 1

            def reset(self):
                self.count = 0
    """)
    assert rules_of(findings) == ["unguarded-shared-attr"]


def test_unknown_guard():
    findings = lint_one("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0  # guarded-by: _mutex

            def get(self):
                with self._lock:
                    return self._x
    """)
    assert "unknown-guard" in rules_of(findings)


# ---------------------------------------------------------- conventions


def test_silent_exception_swallow_outside_shutdown():
    findings = lint_one("""
        def handshake():
            try:
                risky()
            except Exception:
                pass
    """)
    assert rules_of(findings) == ["silent-exception-swallow"]


def test_swallow_allowed_on_shutdown_paths_and_narrow_types():
    findings = lint_one("""
        def close():
            try:
                flush()
            except Exception:
                pass

        def handshake():
            try:
                risky()
            except OSError:
                pass
    """)
    assert findings == []


def test_mutable_default_arg():
    findings = lint_one("""
        def f(xs=[]):
            return xs
    """)
    assert rules_of(findings) == ["mutable-default-arg"]


def test_suffix_reduction_mismatch():
    findings = lint_one("""
        import jax.numpy as jnp

        def metrics(x):
            return {
                "loss_max": jnp.min(x),   # contradicts the suffix
                "loss_min": jnp.min(x),   # coherent
                "steps_sum": jnp.sum(x),  # coherent
            }
    """)
    assert rules_of(findings) == ["suffix-reduction-mismatch"]
    assert len(findings) == 1


# -------------------------------------------------------- donation-safety


def test_use_after_donation_table_method_flagged_rebind_clean():
    # The DONATING_ENTRY_POINTS table holds any `.update_burst(...)`
    # call site to the builder's donate_argnums=(0, 1) contract.
    bad = lint_one("""
        def run(dp, state, buffer, chunk, n):
            out_state, out_buf, m = dp.update_burst(state, buffer, chunk, n)
            return state, m
    """)
    assert rules_of(bad) == ["use-after-donation"]
    good = lint_one("""
        def run(dp, state, buffer, chunk, n):
            state, buffer, m = dp.update_burst(state, buffer, chunk, n)
            return state, m
    """)
    assert good == []


def test_use_after_donation_self_attr_rebind_clean():
    # The host Trainer's exact spelling: self.state/self.buffer donated
    # and rebound by the same statement.
    findings = lint_one("""
        class T:
            def step(self, chunk, n):
                self.state, self.buffer, m = self.dp.update_burst(
                    self.state, self.buffer, chunk, n
                )
                return m
    """)
    assert findings == []


def test_use_after_donation_loop_carry():
    # Donated inside a loop, never rebound in the body: iteration 2
    # passes an already-donated buffer (the PR-1 bug shape, on the
    # donation side).
    findings = lint_one("""
        def run(loop, state, buffer, envs, key, epochs):
            for e in range(epochs):
                out = loop.epoch(state, buffer, envs, key)
            return out
    """)
    assert rules_of(findings) == ["use-after-donation"]
    clean = lint_one("""
        def run(loop, state, buffer, envs, key, epochs):
            for e in range(epochs):
                state, buffer, envs, key, m = loop.epoch(
                    state, buffer, envs, key
                )
            return m
    """)
    assert clean == []


def test_use_after_donation_conditional_and_dict_jit():
    # The serving engine's dict-of-jits with CONDITIONAL donation
    # (`(1,) if donate else ()` — donation happens on accelerators,
    # exactly where the bug bites): reading the padded obs after the
    # subscripted call is flagged; not reading it is clean.
    bad = lint_one("""
        import jax

        def fwd(p, o):
            return o

        class E:
            def build(self, donate):
                self._fwd = {
                    True: jax.jit(fwd, donate_argnums=(1,) if donate else ()),
                    False: jax.jit(fwd, donate_argnums=(1,) if donate else ()),
                }

            def act(self, params, padded):
                out = self._fwd[True](params, padded)
                return out, padded
    """)
    assert rules_of(bad) == ["use-after-donation"]
    good = lint_one("""
        import jax

        def fwd(p, o):
            return o

        class E:
            def build(self, donate):
                self._fwd = {
                    True: jax.jit(fwd, donate_argnums=(1,) if donate else ()),
                }

            def act(self, params, padded):
                out = self._fwd[True](params, padded)
                return out
    """)
    assert good == []


def test_use_after_donation_closure_capture():
    # "captured afterwards" counts: a closure defined after the
    # donating call keeps the dead buffer alive.
    findings = lint_one("""
        def run(dp, state, buffer, chunk, n):
            new_state, new_buf, m = dp.update_burst(state, buffer, chunk, n)

            def report():
                return buffer.size

            return new_state, report
    """)
    assert rules_of(findings) == ["use-after-donation"]


def test_donation_traced_reads_are_not_donation_sites():
    # dynamic_lr_step's shape: TRACED code reading traced values
    # (state.hyperparams per update) never goes through a donating
    # call site — donation analysis applies to host dispatch only.
    findings = lint_one("""
        import jax

        def dynamic_lr_step(updates, opt_state, state):
            lr = state.hyperparams["lr"]
            scaled = jax.tree_util.tree_map(lambda u: u * lr, updates)
            again = state.hyperparams["lr"]
            return scaled, opt_state, again

        step_j = jax.jit(dynamic_lr_step)
    """)
    assert findings == []


def test_undonated_push_flagged_and_donated_clean():
    bad = lint_one("""
        import jax
        from torch_actor_critic_tpu.buffer.replay import push

        push_j = jax.jit(jax.vmap(push))
    """)
    assert rules_of(bad) == ["undonated-push"]
    good = lint_one("""
        import jax
        from torch_actor_critic_tpu.buffer.replay import push

        push_j = jax.jit(jax.vmap(push), donate_argnums=(0,))
    """)
    assert good == []
    # A local function merely NAMED push is not the replay ring.
    local = lint_one("""
        import jax

        def push(buf, chunk):
            return buf

        push_j = jax.jit(push)
    """)
    assert "undonated-push" not in rules_of(local)


def test_stale_donation_table_on_package_runs():
    # A "package" whose builder files are gone: every table row must
    # fail loudly instead of the donation contract silently unchecking.
    findings = lint_sources({
        "torch_actor_critic_tpu/__init__.py": "",
    })
    assert "stale-donation-table" in rules_of(findings)


# ------------------------------------------------------- prng-discipline


def test_key_reuse_two_sinks():
    findings = lint_one("""
        import jax

        def f(key, obs):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a + b
    """)
    assert rules_of(findings) == ["key-reuse"]


def test_key_split_nondestructive():
    findings = lint_one("""
        import jax

        def f(key):
            sub = jax.random.split(key, 2)
            return jax.random.normal(key, (3,)), sub
    """)
    assert rules_of(findings) == ["key-split-nondestructive"]


def test_key_loop_reuse_pr1_engine_regression():
    # THE regression fixture: PR 1's engine warmup reused one key
    # across every bucket's sampled call (donation then deleted the
    # buffer — crash on TPU, silent stream reuse before that).
    bug = lint_one("""
        import jax

        def warmup(act, params, obs, buckets):
            key = jax.random.key(0)
            for b in buckets:
                act(params, obs, key)
    """)
    assert rules_of(bug) == ["key-loop-reuse"]
    # The PR-1 review fix: a fresh subkey per sampled call.
    fixed = lint_one("""
        import jax

        def warmup(act, params, obs, buckets):
            key = jax.random.key(0)
            for b in buckets:
                key, sub = jax.random.split(key)
                act(params, obs, sub)
    """)
    assert fixed == []


def test_key_rules_false_positive_pins():
    # The codebase's sanctioned idioms, pinned clean in one fixture:
    # destructive split, fold_in decorrelation (twice, distinct data),
    # metadata reads, key-array indexing, and struct carries.
    findings = lint_one("""
        import jax
        import jax.numpy as jnp

        def sound(key, state, dev):
            key, sub = jax.random.split(key)
            a = jax.random.normal(sub, (3,))
            local = state.replace(rng=jax.random.fold_in(state.rng, dev))
            out = state.replace(
                rng=jax.random.fold_in(state.rng, jnp.uint32(7))
            )
            keys = jax.random.split(key, 4)
            b = jax.random.normal(keys[0], (3,))
            c = jax.random.normal(keys[1], (3,))
            n = key.shape
            return a, b, c, local, out, n
    """)
    assert findings == []


def test_key_branch_exclusivity():
    # OnDeviceLoop.init's shape: the same parent key split in an
    # early-return arm and again after it — never in sequence.
    findings = lint_one("""
        import jax

        def init(self, k_envs):
            if self.mesh is None:
                return jax.vmap(reset)(jax.random.split(k_envs, 4))
            return jax.vmap(reset)(jax.random.split(k_envs, 8))
    """)
    assert findings == []


def test_key_metadata_and_host_dict_keys_exempt():
    # key_data/key_impl serialization reads are not sinks, and a host
    # function's dict-iteration `key` never qualifies as a PRNG key.
    findings = lint_one("""
        import jax

        def save(key):
            raw = jax.random.key_data(key)
            impl = jax.random.key_impl(key)
            return raw, impl

        def host(metrics):
            out = {}
            for key in metrics:
                out[key] = metrics[key] + len(key)
            return out
    """)
    assert findings == []


# -------------------------------------------------------- contract-drift


def test_contract_table_checked_on_package_runs():
    # A "package" with none of the wiring files: every contract row
    # fails loudly (identity bindings gone).
    findings = lint_sources({
        "torch_actor_critic_tpu/__init__.py": "",
    })
    assert "stale-contract" in rules_of(findings)


def test_contract_rules_skip_partial_runs():
    # A fixture/single-file run cannot tell missing wiring from
    # un-linted wiring — no contract findings.
    findings = lint_one("def f():\n    return 1\n")
    assert not any(
        f.rule in (
            "stale-contract", "missing-watchdog-scope",
            "missing-cost-registration", "incoherent-sharding",
        )
        for f in findings
    )


def test_contract_wiring_satisfiable_in_miniature():
    # A miniature package with one row's full wiring present: the
    # OTHER rows fail (their files are absent) but train/update_burst's
    # scope+registration+sharding checks pass — proving the matchers
    # accept the real spellings (attr identity, Call-receiver .source,
    # hoisted-name register_jit, one-hop planner use).
    findings = lint_sources({
        "torch_actor_critic_tpu/__init__.py": "",
        "torch_actor_critic_tpu/parallel/dp.py": (
            "import jax\n"
            "from torch_actor_critic_tpu.parallel.sharding import "
            "param_specs\n"
            "class DataParallelSAC:\n"
            "    burst_cost_name = 'train/update_burst'\n"
            "    def _state_shardings(self, state):\n"
            "        return param_specs(state, self.mesh, 0)\n"
            "    def _build_burst(self, n, state, buffer, chunk):\n"
            "        sh = self._state_shardings(state)\n"
            "        def burst(state, buffer, chunk):\n"
            "            return state, buffer, {}\n"
            "        return jax.jit(burst, donate_argnums=(0, 1))\n"
        ),
        "torch_actor_critic_tpu/sac/trainer.py": (
            "from torch_actor_critic_tpu.diagnostics.watchdog import "
            "get_watchdog\n"
            "class Trainer:\n"
            "    def train(self):\n"
            "        with get_watchdog().source('train/update_burst'):\n"
            "            pass\n"
            "    def _note_epoch_cost(self, registry):\n"
            "        name = self.dp.burst_cost_name\n"
            "        registry.register_jit(name, None)\n"
        ),
    })
    drifted = {
        f.message.split("'")[1] for f in findings
        if f.rule in (
            "stale-contract", "missing-watchdog-scope",
            "missing-cost-registration", "incoherent-sharding",
        )
    }
    assert "train/update_burst" not in drifted
    assert "serve/forward" in drifted  # its file is absent here


# ----------------------------------------------------------- suppression


def test_suppression_must_name_a_rule():
    findings = lint_one("""
        def f(xs=[]):  # tac-lint: disable
            return xs
    """)
    # The blanket suppression suppresses nothing AND is itself a
    # finding; the mutable default still reports.
    assert rules_of(findings) == ["bare-suppression", "mutable-default-arg"]


def test_suppression_naming_unknown_rule_is_a_finding():
    findings = lint_one("""
        def f(xs=[]):  # tac-lint: disable=definitely-not-a-rule
            return xs
    """)
    assert rules_of(findings) == ["bare-suppression", "mutable-default-arg"]


def test_named_suppression_suppresses_exactly_that_rule():
    findings = lint_one("""
        def f(xs=[]):  # tac-lint: disable=mutable-default-arg
            return xs
    """)
    assert findings == []


# --------------------------------------------------------- whole package


def test_whole_package_and_scripts_clean():
    """THE tier-1 wiring: a new violation anywhere in the package or
    scripts/ fails pytest. Suppression budget (docs/ANALYSIS.md): every
    remaining suppression names a rule (enforced by bare-suppression)
    and the total stays small."""
    findings = lint_paths([str(PKG), str(SCRIPTS)])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_suppression_budget():
    import re

    n = 0
    for f in list(PKG.rglob("*.py")) + list(SCRIPTS.rglob("*.py")):
        if "__pycache__" in f.parts:
            continue
        n += len(re.findall(r"tac-lint:\s*disable=", f.read_text()))
    assert n <= 10, (
        f"{n} tac-lint suppressions in the package/scripts — the "
        "budget is 10, each justified in docs/ANALYSIS.md"
    )


def test_rule_catalog_is_consistent():
    assert ALL_RULES == {
        r for rules in RULE_FAMILIES.values() for r in rules
    }
    # Every family contributes at least one rule and the families the
    # issue names are all present.
    for family in (
        "jit-hygiene", "recompile-risk", "lock-discipline", "conventions",
        "donation-safety", "prng-discipline", "contract-drift",
    ):
        assert RULE_FAMILIES[family]


def test_donation_table_covers_entry_points():
    # Every jit entry point's donation contract is table-checked, and
    # the contract table mirrors ENTRY_POINTS exactly.
    from torch_actor_critic_tpu.analysis.contracts import (
        ENTRY_POINT_CONTRACTS,
    )
    from torch_actor_critic_tpu.analysis.donation import (
        DONATING_ENTRY_POINTS,
    )
    from torch_actor_critic_tpu.analysis.reachability import ENTRY_POINTS

    assert set(ENTRY_POINT_CONTRACTS) == set(ENTRY_POINTS)
    # Donation rows cover every ENTRY_POINTS identity (plus the
    # warmup-path push wrappers, which have no cost identity).
    assert set(ENTRY_POINTS) <= set(DONATING_ENTRY_POINTS)


# ------------------------------------------------------------- CLI (json)


def test_json_mode_per_family_exit_codes(tmp_path, capsys):
    import json

    from torch_actor_critic_tpu.analysis.__main__ import (
        FAMILY_EXIT_CODES,
        main,
    )

    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    assert main(["--json", str(clean)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["clean"] and out["exit_code"] == 0

    conv = tmp_path / "conv.py"
    conv.write_text("def f(xs=[]):\n    return xs\n")
    rc = main(["--json", str(conv)])
    assert rc == FAMILY_EXIT_CODES["conventions"] == 13
    out = json.loads(capsys.readouterr().out)
    assert out["families"]["conventions"] == 1
    assert out["exit_code"] == rc

    prng = tmp_path / "prng.py"
    prng.write_text(
        "import jax\n\n"
        "def f(key):\n"
        "    a = jax.random.normal(key, (3,))\n"
        "    return a + jax.random.uniform(key, (3,))\n"
    )
    assert main(["--json", str(prng)]) == FAMILY_EXIT_CODES[
        "prng-discipline"
    ] == 15
    capsys.readouterr()

    # Mixed families -> the generic failure code 1.
    rc = main(["--json", str(conv), str(prng)])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["exit_code"] == 1 and len(out["findings"]) == 2
