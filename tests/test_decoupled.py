"""Decoupled actor/learner tests: every link's failure mode proven.

The decoupled plane's contract (docs/RESILIENCE.md "Decoupled-plane
failure modes") asserted end-to-end on CPU, with the determinism
discipline of tests/test_resilience.py — injections key off exact step
or call counts, clocks/sleeps are injected, nothing is timing-flaky:

- StagingBuffer: backpressure policies counted, bounded-staleness gate
  drops + bounds the lag histogram, conservation invariant, pause/
  resume, checkpoint array round-trip.
- PolicyClient: the in-process retry/backoff is bounded, deadline-aware
  and taxonomy-preserving (transport parity with PR-9's HTTP mode).
- ActorWorker: degrade-to-snapshot on serving loss (no stalled envs),
  probe-and-re-home, idle-spin against a paused staging buffer.
- DecoupledTrainer: acting through the real serving stack, per-epoch
  validated publish (NaN rejected, last-good keeps serving), SIGTERM →
  requeue → BITWISE resume including the staged-transition tail and
  the serving plane's PRNG stream.
"""

import os
import signal
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.decoupled import (
    ActorWorker,
    DecoupledTrainer,
    StagingBuffer,
    StagingUnavailable,
)
from torch_actor_critic_tpu.diagnostics import EarlyWarningMonitor
from torch_actor_critic_tpu.models import Actor
from torch_actor_critic_tpu.parallel import make_mesh
from torch_actor_critic_tpu.resilience import (
    REQUEUE_EXIT_CODE,
    Preempted,
    PreemptionGuard,
)
from torch_actor_critic_tpu.resilience.faultinject import (
    FaultyEnvPool,
    LossyLink,
    nan_params,
)
from torch_actor_critic_tpu.serve import (
    ModelRegistry,
    PolicyClient,
    PolicyServer,
    ShedError,
)
from torch_actor_critic_tpu.serve.batcher import ActResult
from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
from torch_actor_critic_tpu.utils.config import SACConfig

TINY = dict(
    hidden_sizes=(16, 16),
    batch_size=16,
    epochs=3,
    steps_per_epoch=40,
    start_steps=10,
    update_after=10,
    update_every=10,
    buffer_size=500,
    max_ep_len=100,
    save_every=1,
    decoupled=True,
    max_actor_lag=4,
)


def make_trainer(ckpt_dir, seed=7, preemption=None, client=None, **over):
    cfg = SACConfig(**{**TINY, **over})
    ck = (
        Checkpointer(ckpt_dir, retry_backoff_s=0.0)
        if ckpt_dir is not None
        else None
    )
    return DecoupledTrainer(
        "Pendulum-v1",
        cfg,
        mesh=make_mesh(dp=1),
        checkpointer=ck,
        seed=seed,
        preemption=preemption,
        client=client,
    )


def comparable_state(tr):
    """Every array that defines the learner: full TrainState (PRNG key
    as raw uint32) + the replay ring and its cursors (the pattern of
    tests/test_resilience.py)."""
    s = tr.state
    trees = {
        "actor": s.actor_params,
        "critic": s.critic_params,
        "target": s.target_critic_params,
        "pi_opt": s.pi_opt_state,
        "q_opt": s.q_opt_state,
        "log_alpha": s.log_alpha,
        "alpha_opt": s.alpha_opt_state,
        "step": s.step,
        "rng": jax.random.key_data(s.rng),
        "buffer": tr.buffer.data,
        "ptr": tr.buffer.ptr,
        "size": tr.buffer.size,
    }
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(trees)]


def txn(i, n_envs=1, obs_dim=3, act_dim=1):
    """A tiny distinguishable batched transition."""
    return (
        np.full((n_envs, obs_dim), float(i), np.float32),
        np.full((n_envs, act_dim), float(i), np.float32),
        np.full((n_envs,), float(i), np.float32),
        np.full((n_envs, obs_dim), float(i) + 0.5, np.float32),
        np.zeros((n_envs,), np.float32),
    )


# ------------------------------------------------------------ staging unit


def test_staging_backpressure_shed_and_drop_oldest_counted():
    shed = StagingBuffer(capacity=2, policy="shed")
    assert shed.put(txn(0)) and shed.put(txn(1))
    assert not shed.put(txn(2))  # refused, counted
    assert shed.shed_total == 1 and shed.staged_total == 2
    assert shed.conservation_holds()

    drop = StagingBuffer(capacity=2, policy="drop_oldest")
    assert drop.put(txn(0)) and drop.put(txn(1)) and drop.put(txn(2))
    assert drop.dropped_backpressure_total == 1
    assert drop.staged_total == 3 and drop.depth() == 2
    # Oldest evicted: the queue now holds txns 1 and 2.
    out = drop.pop_window(2)
    assert [int(e.transition[0][0, 0]) for e in out] == [1, 2]
    assert drop.conservation_holds()


def test_staging_block_policy_is_bounded_not_a_deadlock():
    st = StagingBuffer(capacity=1, policy="block", block_timeout_s=0.01)
    assert st.put(txn(0))
    # No consumer: the bounded wait expires and the put is SHED (and
    # counted), never a hang.
    assert not st.put(txn(1))
    assert st.blocked_total == 1 and st.shed_total == 1
    assert st.conservation_holds()


def test_staging_block_policy_wakes_on_drain():
    st = StagingBuffer(capacity=1, policy="block", block_timeout_s=30.0)
    assert st.put(txn(0))
    accepted = []
    done = threading.Event()

    def producer():
        accepted.append(st.put(txn(1)))
        done.set()

    thr = threading.Thread(target=producer, daemon=True)
    thr.start()
    # The producer is parked on backpressure; draining frees a slot.
    assert st.pop_window(1) is not None
    assert done.wait(10.0)
    thr.join(10.0)
    assert accepted == [True]
    assert st.depth() == 1 and st.conservation_holds()


def test_staging_pop_window_is_exact_size_or_none():
    st = StagingBuffer(capacity=10)
    for i in range(3):
        st.put(txn(i))
    assert st.pop_window(4) is None  # partial windows never drain
    assert st.depth() == 3
    out = st.pop_window(3)
    assert [int(e.transition[0][0, 0]) for e in out] == [0, 1, 2]
    with pytest.raises(ValueError):
        st.pop_window(0)


def test_staging_stale_gate_drops_and_bounds_histogram():
    st = StagingBuffer(capacity=16, max_lag=2)
    st.put(txn(0), generation=1, epoch=0)   # lag 5 at epoch 5: stale
    st.put(txn(1), generation=3, epoch=4)   # lag 1: fresh
    st.put(txn(2), generation=4, epoch=5)   # lag 0: fresh
    st.put(txn(3))                          # untagged (warmup): lag 0
    out = st.pop_window(3, current_epoch=5)
    assert [int(e.transition[0][0, 0]) for e in out] == [1, 2, 3]
    assert st.dropped_stale_total == 1
    assert st.conservation_holds()
    # Every recorded lag respects the knob — the acceptance bound.
    snap = st.snapshot()
    assert snap["actor_lag"]["actor_lag_max"] <= 2
    assert snap["actor_lag"]["actor_lag_count"] == 3


def test_staging_pause_blocks_puts_until_resume():
    st = StagingBuffer(capacity=4)
    st.put(txn(0))
    st.pause()
    with pytest.raises(StagingUnavailable):
        st.put(txn(1))
    assert st.depth() == 1  # staged contents survive the pause
    st.resume()
    assert st.put(txn(1))
    assert st.staged_total == 2


def test_staging_drop_oldest_conserves_under_pause_resume_race():
    """Conservation under the worst interleaving: drop_oldest evictions
    racing pause()/resume() flips and a concurrent drainer. Every
    accepted transition must land in exactly one counted outcome —
    drained, dropped_backpressure, or still queued — with no path
    (eviction inside put, StagingUnavailable on a paused buffer,
    pop_window mid-flip) losing or double-counting a row."""
    st = StagingBuffer(capacity=4, policy="drop_oldest")
    n_producers, puts_each = 4, 60
    accepted = [0] * n_producers
    stop_flipping = threading.Event()

    def producer(slot):
        for i in range(puts_each):
            while True:
                try:
                    assert st.put(txn(i))  # drop_oldest always admits
                    accepted[slot] += 1
                    break
                except StagingUnavailable:
                    # Paused mid-run: retry the SAME transition (the
                    # documented actor contract).
                    pass

    def flipper():
        while not stop_flipping.is_set():
            st.pause()
            st.resume()

    drained_windows = [0]
    producers_done = threading.Event()

    def drainer():
        while not (producers_done.is_set() and st.depth() < 2):
            if st.pop_window(2) is not None:
                drained_windows[0] += 1

    threads = [
        threading.Thread(target=producer, args=(s,), daemon=True)
        for s in range(n_producers)
    ]
    threads += [
        threading.Thread(target=flipper, daemon=True),
        threading.Thread(target=drainer, daemon=True),
    ]
    for thr in threads:
        thr.start()
    for thr in threads[:n_producers]:
        thr.join(30.0)
    producers_done.set()
    stop_flipping.set()
    for thr in threads[n_producers:]:
        thr.join(30.0)
    assert all(not thr.is_alive() for thr in threads)

    assert accepted == [puts_each] * n_producers
    assert st.staged_total == n_producers * puts_each
    assert st.drained_total == 2 * drained_windows[0]
    assert not st.paused  # resume() was the flipper's last word
    # The invariant the whole module exists for:
    assert st.conservation_holds()
    snap = st.snapshot()
    assert snap["staged_total"] == (
        snap["drained_total"]
        + snap["dropped_backpressure_total"]
        + snap["depth"]
    )


def test_staging_checkpoint_arrays_roundtrip_is_bitwise():
    st = StagingBuffer(capacity=8, max_lag=3)
    st.put(txn(0), generation=2, epoch=1)
    st.put(txn(1), generation=3, epoch=2)
    st.put(txn(2))  # untagged
    st.pop_window(1, current_epoch=2)  # make the counters non-trivial
    arrays = st.export_arrays()
    meta = st.meta_state()
    assert meta["count"] == 2

    st2 = StagingBuffer(capacity=8, max_lag=3)
    st2.load_meta(meta)
    assert st2.import_arrays(arrays) == 2
    assert st2.staged_total == st.staged_total
    assert st2.drained_total == st.drained_total
    assert st2.lag_hist.count == st.lag_hist.count
    a = list(st._q)
    b = list(st2._q)
    assert len(a) == len(b) == 2
    for ea, eb in zip(a, b):
        assert ea.generation == eb.generation
        assert ea.epoch == eb.epoch
        for xa, xb in zip(ea.transition, eb.transition):
            np.testing.assert_array_equal(xa, xb)
    # An empty buffer exports no arrays item at all.
    empty = StagingBuffer(capacity=2)
    assert empty.export_arrays() is None


# --------------------------------------------- in-process client retry


class _ScriptedBatcher:
    """Raises a scripted exception sequence from act(), then succeeds."""

    def __init__(self, errors):
        self.errors = list(errors)
        self.calls = 0
        self.timeouts = []

    def act(self, obs, deterministic=True, slot="default", timeout=None,
            request_id=None):
        self.calls += 1
        self.timeouts.append(timeout)
        if self.errors:
            raise self.errors.pop(0)
        return ActResult(np.zeros((1, 2), np.float32), 5, 9)


def test_inprocess_client_retries_sheds_with_backoff_and_hint():
    sleeps = []
    batcher = _ScriptedBatcher([
        ShedError("queue_full", "full", retry_after_s=0.5),
        ShedError("breaker_open", "open", retry_after_s=0.0),
    ])
    client = PolicyClient(
        ModelRegistry(), batcher, retries=3, backoff_s=0.25,
        sleep=sleeps.append,
    )
    res = client.act(np.zeros(2), timeout=60.0)
    assert res.generation == 5 and res.epoch == 9
    assert batcher.calls == 3
    assert client.retries_total == 2
    # Delay honors max(hint, backoff*2^n) with <=25% jitter — exactly
    # the HTTP-mode ladder.
    assert 0.5 <= sleeps[0] <= 0.5 * 1.25
    assert 0.5 <= sleeps[1] <= 0.5 * 1.25  # backoff 0.25*2 vs hint 0
    # The per-attempt timeout shrinks toward the caller's deadline.
    assert all(t_ is not None and t_ <= 60.0 for t_ in batcher.timeouts)


def test_inprocess_client_retry_is_bounded_and_taxonomy_preserved():
    batcher = _ScriptedBatcher([
        ShedError("queue_full", "full", retry_after_s=0.0)
        for _ in range(10)
    ])
    client = PolicyClient(
        ModelRegistry(), batcher, retries=2, backoff_s=0.0,
        sleep=lambda s: None,
    )
    with pytest.raises(ShedError) as ei:
        client.act(np.zeros(2), timeout=60.0)
    assert ei.value.reason == "queue_full"  # the LAST rejection, intact
    assert batcher.calls == 3  # 1 + retries, never more


def test_inprocess_client_never_retries_past_the_deadline():
    sleeps = []
    batcher = _ScriptedBatcher([
        ShedError("queue_full", "full", retry_after_s=500.0),
    ])
    client = PolicyClient(
        ModelRegistry(), batcher, retries=5, backoff_s=0.25,
        sleep=sleeps.append,
    )
    with pytest.raises(ShedError) as ei:
        client.act(np.zeros(2), timeout=0.2)
    # The 500s Retry-After cannot fit a 0.2s budget: the rejection is
    # raised immediately, with zero sleeping past the deadline.
    assert ei.value.reason == "queue_full"
    assert sleeps == []
    assert batcher.calls == 1


def test_inprocess_client_does_not_retry_request_errors():
    batcher = _ScriptedBatcher([ValueError("bad obs shape")])
    client = PolicyClient(
        ModelRegistry(), batcher, retries=5, sleep=lambda s: None
    )
    with pytest.raises(ValueError):
        client.act(np.zeros(2), timeout=5.0)
    assert batcher.calls == 1


# -------------------------------------------------- actor worker / link


class _FakeClient:
    def __init__(self):
        self.fail_left = 0
        self.calls = 0
        self.retries_total = 0

    def act(self, obs, deterministic=True, slot="default", timeout=None,
            request_id=None):
        self.calls += 1
        if self.fail_left:
            self.fail_left -= 1
            raise ConnectionError("injected connection loss")
        return ActResult(np.asarray(obs) * 0.0, 7, 3)


def _fallback(obs, deterministic):
    return np.asarray(obs) * 0.0 + 1.0, 2, 1


def test_actor_degrades_probes_and_rehomes():
    client = _FakeClient()
    staging = StagingBuffer(capacity=8)
    actor = ActorWorker(
        client, staging, fallback=_fallback, probe_every=3,
        sleep=lambda s: None,
    )
    obs = np.zeros((1, 3), np.float32)
    client.fail_left = 4
    # First failure: degrade, stamped with the SNAPSHOT's tags.
    actions, gen, epoch, src = actor.act(obs)
    assert src == "fallback" and (gen, epoch) == (2, 1)
    assert actor.degraded and actor.degradations_total == 1
    # While degraded, only every probe_every-th call touches serving.
    calls_before = client.calls
    assert actor.act(obs)[3] == "fallback"
    assert actor.act(obs)[3] == "fallback"
    assert client.calls == calls_before  # no serving attempts between probes
    probe = actor.act(obs)  # 3rd degraded step: probe (fails, 3 left->2)
    assert probe[3] == "fallback" and actor.probes_total == 1
    actor.act(obs), actor.act(obs)
    rehomed = actor.act(obs)  # next probe: fail budget spent -> success
    # fail_left was 4: initial + first probe consumed 2... walk until
    # re-homed to stay robust to the exact probe arithmetic:
    for _ in range(12):
        if not actor.degraded:
            break
        rehomed = actor.act(obs)
    assert not actor.degraded
    assert actor.rehomes_total == 1
    assert rehomed[3] == "serving" and rehomed[1] == 7 and rehomed[2] == 3
    assert actor.fallback_actions_total >= 4


def test_actor_without_fallback_surfaces_the_failure():
    client = _FakeClient()
    client.fail_left = 1
    actor = ActorWorker(client, StagingBuffer(capacity=2), fallback=None)
    with pytest.raises(ConnectionError):
        actor.act(np.zeros((1, 3), np.float32))


def test_actor_idle_spins_while_paused_and_reconnects():
    staging = StagingBuffer(capacity=8)
    actor = ActorWorker(
        _FakeClient(), staging, fallback=_fallback,
        idle_backoff_s=0.0, sleep=lambda s: None,
    )
    staging.pause()
    stop = threading.Event()
    done = threading.Event()
    result = []

    def worker():
        result.append(actor.stage(txn(0), generation=1, epoch=0, stop=stop))
        done.set()

    thr = threading.Thread(target=worker, daemon=True)
    thr.start()
    # The actor is spinning against the paused buffer, losing nothing.
    import time as _time

    t_end = _time.monotonic() + 10.0
    while actor.idle_spins_total == 0 and _time.monotonic() < t_end:
        _time.sleep(0)  # yield to the spinning thread
    assert actor.idle_spins_total >= 1
    assert not done.is_set()
    staging.resume()
    assert done.wait(10.0)
    thr.join(10.0)
    assert result == [True]
    assert staging.depth() == 1  # the SAME transition arrived post-resume
    assert actor.idle_spins_total >= 1


def test_lossy_link_injects_latency_and_drops_standalone():
    class _Echo:
        def act(self, obs, **kw):
            return ActResult(np.asarray(obs), 1, None)

    slept = []
    link = LossyLink(_Echo(), latency_s=0.25, sleep=slept.append)
    link.drop_next(2)
    with pytest.raises(OSError):
        link.act(np.zeros(2))
    with pytest.raises(OSError):
        link.act(np.zeros(2))
    out = link.act(np.ones(2))
    assert out.generation == 1
    assert link.calls_total == 3 and link.drops_injected == 2
    assert slept == [0.25, 0.25, 0.25]  # every call pays the link latency
    # Probabilistic mode is seedable (deterministic under a fixed rng).
    import random

    link2 = LossyLink(
        _Echo(), drop_rate=1.0, rng=random.Random(0), sleep=lambda s: None
    )
    with pytest.raises(OSError):
        link2.act(np.zeros(2))
    with pytest.raises(ValueError):
        LossyLink(_Echo(), drop_rate=1.5)


def test_lag_drift_feeds_early_warning_monitor():
    mon = EarlyWarningMonitor(warmup=2)
    fired = []
    for lag in (1.0, 1.0, 1.0, 1.0, 40.0):
        fired += mon.update({"decoupled/actor_lag_mean": lag})
    assert any(w["kind"] == "actor_lag_drift" for w in fired)


# ----------------------------------------------- epoch on the wire


def test_actresult_carries_publish_epoch_inprocess_and_http():
    actor = Actor(act_dim=2, hidden_sizes=(8, 8))
    params = actor.init(
        jax.random.key(0), jnp.zeros((3,)), jax.random.key(1)
    )
    reg = ModelRegistry()
    reg.register(
        "default", actor, jax.ShapeDtypeStruct((3,), jnp.float32),
        params=params, max_batch=2,
    )
    staging = StagingBuffer(capacity=4, max_lag=2)
    staging.put(txn(0), generation=1, epoch=7)
    srv = PolicyServer(
        reg, port=0,
        extra_snapshot=lambda: {"decoupled": staging.snapshot()},
    ).start()
    try:
        # Directly-seeded slot: no epoch yet.
        res = srv.client.act(np.zeros(3, np.float32))
        assert res.epoch is None and res.generation == 0
        # A publish stamps every subsequent response, both transports.
        reg.swap("default", params, epoch=7)
        res = srv.client.act(np.zeros(3, np.float32))
        assert res.epoch == 7 and res.generation == 1
        http = PolicyClient(url=srv.address, retries=0)
        res = http.act(np.zeros(3, np.float32))
        assert res.epoch == 7 and res.generation == 1
        # The staging snapshot rides /metrics via extra_snapshot: the
        # actor-lag histogram is observable next to serving metrics.
        import json
        from urllib import request as urlreq

        with urlreq.urlopen(f"{srv.address}/metrics", timeout=10) as r:
            snap = json.loads(r.read())
        assert "decoupled" in snap
        assert "actor_lag_count" in snap["decoupled"]["actor_lag"]
    finally:
        srv.close()


# ------------------------------------------------- trainer end-to-end


def test_decoupled_trainer_trains_through_the_serving_plane(tmp_path):
    tr = make_trainer(tmp_path / "ck", epochs=2)
    try:
        m = tr.train()
        assert np.isfinite(m["loss_q"])
        # Every policy action post-warmup went through the serving
        # stack and every transition is accounted for.
        assert tr.actor.serving_actions_total > 0
        assert m["decoupled/staged_total"] == 80
        assert tr.staging.conservation_holds()
        assert m["decoupled/actor_lag_max"] <= TINY["max_actor_lag"]
        # One validated publish per epoch; the slot tracks the epoch.
        assert m["decoupled/published_generation"] == 2
        assert tr.registry.epoch_of("default") == 1
    finally:
        tr.close()


def test_stale_gate_drops_in_the_real_loop(tmp_path):
    # max_actor_lag=0: after the first publish every transition is one
    # epoch stale at drain time, so the gate drops them and windows are
    # SKIPPED (shape-stable) — off-policy drift as a hard knob.
    tr = make_trainer(tmp_path / "ck", epochs=3, max_actor_lag=0)
    try:
        m = tr.train()
        assert np.isfinite(m["loss_q"])
        assert m["decoupled/dropped_stale_total"] > 0
        assert tr.staging.conservation_holds()
        assert m["decoupled/actor_lag_max"] == 0.0
    finally:
        tr.close()


def test_serving_loss_degrades_and_run_completes(tmp_path):
    tr = make_trainer(tmp_path / "ck", epochs=2)
    # Sever the actor↔serving link from lockstep step 20 on: the link
    # drops every later call, actors degrade to the local snapshot and
    # envs never stall.
    link = LossyLink(tr.client).drop_next(10_000)
    tr.pool = FaultyEnvPool(tr.pool).call_at(
        20, lambda: setattr(tr.actor, "client", link)
    )
    try:
        m = tr.train()
        assert np.isfinite(m["loss_q"])
        assert tr.actor.degradations_total >= 1
        assert m["decoupled/fallback_actions_total"] > 0
        assert m["decoupled/degraded"] == 1.0
        # Degraded transitions are stamped with the published epoch, so
        # staleness stays bounded (the learner keeps publishing).
        assert m["decoupled/actor_lag_max"] <= TINY["max_actor_lag"]
        assert tr.staging.conservation_holds()
    finally:
        tr.close()


def test_nan_publish_is_rejected_and_last_good_serves(tmp_path):
    tr = make_trainer(None, sentinel=False)
    try:
        host = tr._fetch_params_single_transfer()
        gen0 = tr.registry.swap("default", host, epoch=0)
        tr._published_generation = 1
        # Poison the learner's actor params (the state a NaN epoch
        # would publish) and run the publish path.
        tr.state = tr.state.replace(
            actor_params=jax.tree_util.tree_map(
                jnp.asarray, nan_params(host)
            )
        )
        tr._host_params = None
        tr._publish_epoch(1, saved=False)
        assert tr._publish_rejected_total == 1
        assert tr._published_generation == 1  # no new generation
        # The slot still serves the last-good params/epoch.
        _, params, gen = tr.registry.acquire("default")
        assert gen == gen0
        assert tr.registry.epoch_of("default") == 0
        assert all(
            np.isfinite(np.asarray(x)).all()
            for x in jax.tree_util.tree_leaves(params)
        )
    finally:
        tr.close()


def test_decoupled_sigterm_resume_is_bitwise_including_staging(tmp_path):
    """The acceptance bitwise proof: SIGTERM mid-epoch-1, requeue exit,
    resume — the final learner state AND replay stream are bitwise
    identical to an uninterrupted run. steps_per_epoch=44 leaves the
    epoch-1 boundary (step 88) 8 transitions past the last window
    drain (step 80), so the checkpointed staging tail (and the serving
    plane's PRNG stream) is part of what must round-trip."""
    over = dict(epochs=3, steps_per_epoch=44, save_every=10)

    tra = make_trainer(tmp_path / "a", **over)
    try:
        tra.train()
        ref = comparable_state(tra)
        ref_staged = tra.staging.staged_total
    finally:
        tra.close()

    guard = PreemptionGuard().install()
    trb = make_trainer(tmp_path / "b", preemption=guard, **over)
    trb.pool = FaultyEnvPool(trb.pool).call_at(
        50, lambda: os.kill(os.getpid(), signal.SIGTERM)
    )
    try:
        with pytest.raises(Preempted) as ei:
            trb.train()
    finally:
        guard.uninstall()
        trb.close()
    assert ei.value.exit_code == REQUEUE_EXIT_CODE
    meta = trb.checkpointer.peek_meta()
    assert meta["epoch"] == 1
    dec = meta["decoupled"]
    assert dec["staging"]["count"] == 8  # the undrained tail is saved
    assert dec["batcher_key"]  # the serving PRNG stream is part of it

    trc = make_trainer(tmp_path / "b", **{**over, "epochs": 1})
    try:
        assert trc.restore() == 2
        assert trc.staging.depth() == 8  # zero accepted transitions lost
        trc.train()
        got = comparable_state(trc)
        assert trc.staging.staged_total == ref_staged
        assert trc.staging.conservation_holds()
    finally:
        trc.close()
    for x, y in zip(ref, got, strict=True):
        np.testing.assert_array_equal(x, y)
