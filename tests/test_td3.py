"""TD3 extension: update semantics, delay cadence, and the full loop.

The reference is SAC-only; these tests pin the second algorithm family
against the canonical TD3 semantics (Fujimoto et al. 2018) and prove it
rides the same burst/mesh/Trainer machinery as SAC.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.buffer import init_replay_buffer, push
from torch_actor_critic_tpu.core.types import Batch
from torch_actor_critic_tpu.models import DeterministicActor, DoubleCritic
from torch_actor_critic_tpu.td3 import TD3, losses
from torch_actor_critic_tpu.utils.config import SACConfig

OBS_DIM, ACT_DIM = 4, 2


def make_td3(**overrides):
    cfg = SACConfig(
        algorithm="td3", hidden_sizes=(32, 32), batch_size=8, **overrides
    )
    actor = DeterministicActor(
        act_dim=ACT_DIM, hidden_sizes=cfg.hidden_sizes,
        act_limit=1.0, act_noise=cfg.act_noise,
    )
    critic = DoubleCritic(hidden_sizes=cfg.hidden_sizes, num_qs=cfg.num_qs)
    return TD3(cfg, actor, critic, ACT_DIM)


def make_batch(key, n=8):
    ks = jax.random.split(key, 5)
    return Batch(
        states=jax.random.normal(ks[0], (n, OBS_DIM)),
        actions=jnp.tanh(jax.random.normal(ks[1], (n, ACT_DIM))),
        rewards=jax.random.normal(ks[2], (n,)),
        next_states=jax.random.normal(ks[3], (n, OBS_DIM)),
        done=jnp.zeros((n,)),
    )


def test_deterministic_actor_contract():
    """Noiseless when deterministic; clipped noisy exploration
    otherwise; key required only for exploration."""
    actor = DeterministicActor(act_dim=ACT_DIM, hidden_sizes=(16,),
                               act_limit=2.0, act_noise=0.3)
    params = actor.init(jax.random.key(0), jnp.zeros((OBS_DIM,)), None,
                        deterministic=True)
    obs = jax.random.normal(jax.random.key(1), (5, OBS_DIM))
    a_det, logp = actor.apply(params, obs, None, deterministic=True)
    assert logp is None
    assert a_det.shape == (5, ACT_DIM)
    assert float(jnp.max(jnp.abs(a_det))) <= 2.0
    a1 = actor.apply(params, obs, jax.random.key(2))[0]
    a2 = actor.apply(params, obs, jax.random.key(3))[0]
    assert float(jnp.max(jnp.abs(a1 - a_det))) > 0  # noise applied
    assert float(jnp.max(jnp.abs(a1 - a2))) > 0     # key-dependent
    assert float(jnp.max(jnp.abs(a1))) <= 2.0       # clipped to the box
    with pytest.raises(ValueError, match="PRNG key"):
        actor.apply(params, obs, None)


def test_target_smoothing_reduces_to_deterministic_backup():
    """With noise_clip=0 the smoothing noise vanishes: the critic loss
    must equal the zero-target-noise one exactly."""
    td3 = make_td3()
    state = td3.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    batch = make_batch(jax.random.key(1))

    def loss_with(target_noise, noise_clip):
        loss, _ = losses.critic_loss(
            state.critic_params,
            actor_apply=td3._actor_apply,
            critic_apply=td3._critic_apply,
            target_actor_params=state.target_actor_params,
            target_critic_params=state.target_critic_params,
            batch=batch,
            key=jax.random.key(2),
            act_limit=1.0,
            target_noise=target_noise,
            noise_clip=noise_clip,
            gamma=0.99,
            reward_scale=1.0,
        )
        return float(loss)

    assert loss_with(0.5, 0.0) == loss_with(0.0, 0.5)
    # And with real smoothing the loss differs (noise actually flows).
    assert loss_with(0.5, 0.5) != loss_with(0.0, 0.5)


def test_policy_delay_cadence():
    """With policy_delay=d: actor params, policy opt state and BOTH
    target nets change only on every d-th update; the critic changes
    every update."""
    td3 = make_td3(policy_delay=3)
    state = td3.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    update = jax.jit(td3.update)

    def leaf0(tree):
        return np.asarray(jax.tree_util.tree_leaves(tree)[0])

    for i in range(1, 7):
        prev = state
        state, m = update(state, make_batch(jax.random.key(100 + i)))
        critic_moved = not np.allclose(leaf0(prev.critic_params),
                                       leaf0(state.critic_params))
        actor_moved = not np.allclose(leaf0(prev.actor_params),
                                      leaf0(state.actor_params))
        targ_pi_moved = not np.allclose(leaf0(prev.target_actor_params),
                                        leaf0(state.target_actor_params))
        targ_q_moved = not np.allclose(leaf0(prev.target_critic_params),
                                       leaf0(state.target_critic_params))
        opt_count = int(jax.tree_util.tree_leaves(state.pi_opt_state)[0])
        assert critic_moved
        expected = i % 3 == 0
        assert actor_moved == expected, i
        assert targ_pi_moved == expected, i
        assert targ_q_moved == expected, i
        # Adam count advances only on applied policy updates.
        assert opt_count == i // 3, (i, opt_count)


def test_update_burst_runs_and_learns():
    """The shared push-then-scan burst drives TD3: with gamma=0 the
    critic is pure regression onto a deterministic reward function, so
    its loss must fall over repeated bursts (with bootstrapped targets
    the loss needn't be monotone, hence the gamma=0 construction)."""
    td3 = make_td3(gamma=0.0)
    state = td3.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    buf = init_replay_buffer(
        512, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM
    )

    def chunk(key, n):
        b = make_batch(key, n=n)
        return b.replace(
            rewards=jnp.sum(b.states, -1) + jnp.sum(b.actions, -1)
        )

    buf = push(buf, chunk(jax.random.key(5), 128))
    burst = jax.jit(td3.update_burst, static_argnums=(3,))
    first = None
    for i in range(20):
        state, buf, m = burst(state, buf, chunk(jax.random.key(10 + i), 10), 10)
        if first is None:
            first = float(m["loss_q"])
    assert float(m["loss_q"]) < first
    assert int(state.step) == 200


def make_dp_chunk(key, n_dev, per_dev):
    ks = jax.random.split(key, 5)
    shape = (n_dev, per_dev)
    return Batch(
        states=jax.random.normal(ks[0], shape + (OBS_DIM,)),
        actions=jnp.tanh(jax.random.normal(ks[1], shape + (ACT_DIM,))),
        rewards=jax.random.normal(ks[2], shape),
        next_states=jax.random.normal(ks[3], shape + (OBS_DIM,)),
        done=jnp.zeros(shape),
    )


def test_td3_under_data_parallel_mesh():
    """TD3 slots into the same mesh wrapper as SAC: a dp burst on the
    8-virtual-device mesh runs and keeps params replicated."""
    from torch_actor_critic_tpu.parallel import (
        DataParallelSAC,
        init_sharded_buffer,
        make_mesh,
        shard_chunk,
    )

    td3 = make_td3()
    dp = DataParallelSAC(td3, make_mesh())
    state = dp.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    buf = init_sharded_buffer(
        128, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM, dp.mesh
    )
    warm = shard_chunk(make_dp_chunk(jax.random.key(1), 8, 32), dp.mesh)
    chunk = shard_chunk(make_dp_chunk(jax.random.key(2), 8, 10), dp.mesh)
    state, buf, _ = dp.update_burst(state, buf, warm, 1)
    state, buf, m = dp.update_burst(state, buf, chunk, 5)
    assert np.isfinite(float(m["loss_q"]))
    assert int(state.step) == 6
    leaf = jax.tree_util.tree_leaves(state.target_actor_params)[0]
    assert leaf.sharding.is_fully_replicated


def test_td3_trainer_end_to_end(tmp_path):
    """Full Trainer loop on Pendulum with algorithm='td3': runs, logs
    both losses, checkpoints (incl. target actor), resumes."""
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer

    cfg = SACConfig(
        algorithm="td3", epochs=1, steps_per_epoch=120, start_steps=40,
        update_after=40, update_every=20, batch_size=16,
        hidden_sizes=(32, 32), buffer_size=2000, max_ep_len=100,
        save_every=1,
    )
    ckpt = Checkpointer(tmp_path / "ckpt")
    tr = Trainer("Pendulum-v1", cfg, checkpointer=ckpt, seed=0)
    metrics = tr.train()
    assert np.isfinite(metrics["loss_q"]) and np.isfinite(metrics["loss_pi"])
    assert int(tr.state.step) > 0

    tr2 = Trainer(
        "Pendulum-v1", cfg, checkpointer=Checkpointer(tmp_path / "ckpt"), seed=0
    )
    tr2.restore()
    # The restored state carries the TD3-only target actor subtree and
    # the trained step counter.
    assert tr2.state.target_actor_params is not None
    assert int(tr2.state.step) == int(tr.state.step)

    # Cross-algorithm restore fails with a clear message BEFORE the
    # array restore (a SAC trainer lacks the target-actor subtree).
    sac_cfg = cfg.replace(algorithm="sac")
    tr3 = Trainer(
        "Pendulum-v1", sac_cfg,
        checkpointer=Checkpointer(tmp_path / "ckpt"), seed=0,
    )
    with pytest.raises(ValueError, match="algorithm='td3'"):
        tr3.restore()
    ckpt.close()


@pytest.mark.slow
def test_td3_solves_pendulum():
    """Convergence: TD3 through the product Trainer reaches the solved
    band on Pendulum (deterministic eval; measured -132 mean over 10
    episodes at this config on CPU — the bound is deliberately loose
    against seed variance)."""
    from torch_actor_critic_tpu.sac.trainer import Trainer

    cfg = SACConfig(
        algorithm="td3", epochs=6, steps_per_epoch=2500, start_steps=1000,
        update_after=1000, update_every=50, batch_size=64, max_ep_len=200,
    )
    tr = Trainer("Pendulum-v1", cfg, seed=0)
    tr.train()
    ev = tr.evaluate(episodes=10, deterministic=True, seed=0)
    assert ev["ep_ret_mean"] > -400, ev
    tr.close()


def test_td3_visual_stack_and_sequence_rejection():
    """Visual TD3: build_models dispatches a DeterministicVisualActor +
    VisualDoubleCritic on mixed observations and the learner takes a
    gradient step; the sequence (history) stack stays SAC-only with a
    construction-time error."""
    from test_visual_training import FakeVisualEnv

    from torch_actor_critic_tpu.core.types import MultiObservation
    from torch_actor_critic_tpu.models import (
        DeterministicVisualActor,
        VisualDoubleCritic,
    )
    from torch_actor_critic_tpu.sac.trainer import build_models, make_learner

    cfg = SACConfig(
        algorithm="td3", hidden_sizes=(16, 16), batch_size=4,
        filters=(8, 16), kernel_sizes=(4, 3), strides=(2, 1),
        normalize_pixels=True,
    )
    env = FakeVisualEnv()
    actor, critic = build_models(cfg, env)
    assert isinstance(actor, DeterministicVisualActor)
    assert isinstance(critic, VisualDoubleCritic)
    td3 = make_learner(cfg, actor, critic, env.act_dim)
    example = MultiObservation(
        features=jnp.zeros((6,)), frame=jnp.zeros((16, 16, 3), jnp.uint8)
    )
    state = td3.init_state(jax.random.key(0), example)
    ks = jax.random.split(jax.random.key(1), 6)
    n = 4
    batch = Batch(
        states=MultiObservation(
            features=jax.random.normal(ks[0], (n, 6)),
            frame=jax.random.randint(ks[1], (n, 16, 16, 3), 0, 256, jnp.uint8),
        ),
        actions=jnp.tanh(jax.random.normal(ks[2], (n, 3))),
        rewards=jax.random.normal(ks[3], (n,)),
        next_states=MultiObservation(
            features=jax.random.normal(ks[4], (n, 6)),
            frame=jax.random.randint(ks[5], (n, 16, 16, 3), 0, 256, jnp.uint8),
        ),
        done=jnp.zeros((n,)),
    )
    state, m = jax.jit(td3.update)(state, batch)
    assert np.isfinite(float(m["loss_q"]))

    class _HistoryEnv:
        obs_spec = jax.ShapeDtypeStruct((8, 4), jnp.float32)
        act_dim = 2
        act_limit = 1.0

    with pytest.raises(ValueError, match="sequence"):
        build_models(SACConfig(algorithm="td3"), _HistoryEnv())


def test_ddpg_degenerate_config():
    """DDPG is TD3's degenerate corner: policy_delay=1, target_noise=0,
    num_qs=1 (min over one head = plain Q). Pin that the corner runs —
    the framework gets a third classical algorithm for free."""
    td3 = make_td3(policy_delay=1, target_noise=0.0, num_qs=1)
    state = td3.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    update = jax.jit(td3.update)
    prev = state
    state, m = update(state, make_batch(jax.random.key(1)))
    assert np.isfinite(float(m["loss_q"]))
    # policy_delay=1: the actor moves on every update.
    a0 = jax.tree_util.tree_leaves(prev.actor_params)[0]
    a1 = jax.tree_util.tree_leaves(state.actor_params)[0]
    assert not np.allclose(np.asarray(a0), np.asarray(a1))


def test_config_rejects_bad_algorithm():
    with pytest.raises(ValueError, match="algorithm"):
        SACConfig(algorithm="ppo")
    with pytest.raises(ValueError, match="policy_delay"):
        SACConfig(policy_delay=0)
    # SAC-only opt-ins must fail at construction under td3, not be
    # silently inert (same policy as the visual/sequence stack gate).
    with pytest.raises(ValueError, match="SAC-only"):
        SACConfig(algorithm="td3", learn_alpha=True)
    with pytest.raises(ValueError, match="SAC-only"):
        SACConfig(algorithm="td3", parity_pi_obs=True)
