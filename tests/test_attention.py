"""Attention stack: blockwise/flash kernels, sequence models, ring
context parallelism.

All extension capability (the reference has no attention or sequence
axis — SURVEY.md §5), tested the way the distributed suite tests DP:
exact numerics against a dense reference, and real collective semantics
on the 8-virtual-device CPU mesh from ``conftest.py``. The Pallas
kernel runs in interpreter mode here (same kernel code path the TPU
compiles).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.models import SequenceActor, SequenceDoubleCritic
from torch_actor_critic_tpu.parallel.context import manual_shard_map as shard_map
from torch_actor_critic_tpu.ops.attention import (
    attention,
    blockwise_attention,
    flash_attention,
    reference_attention,
)
from torch_actor_critic_tpu.parallel import make_mesh
from torch_actor_critic_tpu.parallel.context import (
    context_parallel_actor_step,
    ring_attention,
)
from jax.sharding import PartitionSpec as P


def qkv(seed, b=2, h=2, t=32, d=16):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, h, t, d)
    return tuple(jax.random.normal(k, shape) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_k", [8, 16, 13])  # 13: pad-tail path
def test_blockwise_matches_reference(causal, block_k):
    q, k, v = qkv(0, t=40)
    expected = reference_attention(q, k, v, causal=causal)
    got = blockwise_attention(q, k, v, causal=causal, block_k=block_k)
    np.testing.assert_allclose(got, expected, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_matches_reference(causal):
    q, k, v = qkv(1, t=32, d=16)
    expected = reference_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, 8, 8, True)  # interpret mode
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_pallas_dispatch_off_tpu_fails_loudly():
    """VERDICT r2 weak #7: requesting the TPU (Pallas) kernel from a
    process whose default backend is CPU must raise a clear trace-time
    RuntimeError naming the fix — not a cryptic Mosaic lowering error
    (the 'auto'-dispatch footgun documented on attention())."""
    q, k, v = qkv(3)
    with pytest.raises(RuntimeError, match="default backend is 'cpu'"):
        flash_attention(q, k, v, False, 8, 8)  # compiled mode, no TPU
    # Same guard through the dispatcher inside a jit trace — the shape a
    # user hits when a sequence model built for TPU is jitted on CPU.
    with pytest.raises(RuntimeError, match="impl='xla'"):
        jax.jit(lambda q, k, v: attention(q, k, v, impl="pallas"))(q, k, v)


def test_auto_block_selection():
    """Default (None) block sizes resolve to the largest of
    {128, 256, 512} tiling the sequence — the chip block-sweep optimum
    — while accepting EXACTLY the shape set the old fixed-128 default
    did: shapes the old default sent to XLA (or rejected) must not
    silently acquire degenerate Pallas tiles."""
    from torch_actor_critic_tpu.ops.attention import _auto_block, _check_blocks

    assert _auto_block(2048) == 512
    assert _auto_block(8192) == 512
    assert _auto_block(640) == 128   # 640 % 512 != 0, 640 % 128 == 0
    assert _auto_block(64) == 64     # <= 128: one block, as before
    # Old default rejected these (not 128-divisible, > 128): auto must
    # too, not hand them 8-wide tiles the chip never validated.
    assert _auto_block(264) is None
    assert _auto_block(1032) is None
    assert _check_blocks(1024, 640, None, None) == (512, 128)
    with pytest.raises(ValueError, match="ragged"):
        _check_blocks(1032, 1032, None, None)
    # Explicit values still pass through (and still validate).
    assert _check_blocks(1024, 1024, 128, 256) == (128, 256)
    # The dispatcher routes auto-rejected lengths to XLA (same result,
    # no Pallas trace — this would raise off-TPU if it tried Pallas).
    q, k, v = qkv(9, t=264)
    np.testing.assert_allclose(
        attention(q, k, v, causal=True),
        reference_attention(q, k, v, causal=True),
        atol=1e-5,
    )
    # Auto equals explicit at the resolved sizes in interpret mode.
    q, k, v = qkv(7, t=24)  # 24 <= 128 -> single (24, 24) block
    np.testing.assert_allclose(
        flash_attention(q, k, v, True, None, None, True),
        flash_attention(q, k, v, True, 24, 24, True),
        atol=1e-6,
    )


def test_flash_rejects_ragged_lengths():
    q, k, v = qkv(20, t=20)  # 20 % 8 != 0
    with pytest.raises(ValueError, match="ragged"):
        flash_attention(q, k, v, False, 8, 8, True)


def test_flash_pads_head_dim():
    # d=16 is not lane-aligned; the wrapper zero-pads to 128 and slices.
    q, k, v = qkv(21, t=16, d=16)
    expected = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, True, 8, 8, True)
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_flash_pad_lanes_64_matches_reference():
    """pad_lanes=64 keeps a d=64 head at true width (half the HBM
    traffic of the zero-padded layout); math must be identical, fwd
    and bwd."""
    q, k, v = qkv(31, t=32, d=64)
    expected = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, True, 8, 8, True, 64)
    np.testing.assert_allclose(got, expected, atol=1e-5)

    def loss(fn):
        return jax.grad(
            lambda q: jnp.sum(fn(q) ** 2)
        )(q)

    g64 = loss(lambda q: flash_attention(q, k, v, True, 8, 8, True, 64))
    g128 = loss(lambda q: flash_attention(q, k, v, True, 8, 8, True, 128))
    np.testing.assert_allclose(g64, g128, atol=1e-5)

    # d=48 actually exercises the lanes=64 pad/slice branch (d=64 is a
    # no-op there): pad 48 -> 64, output sliced back to 48.
    q48, k48, v48 = qkv(33, t=16, d=48)
    np.testing.assert_allclose(
        flash_attention(q48, k48, v48, True, 8, 8, True, 64),
        reference_attention(q48, k48, v48, causal=True),
        atol=1e-5,
    )


def test_flash_gradients_match_reference():
    q, k, v = qkv(2, b=1, h=1, t=16, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 8, 8, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(gf, gr, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,d,blk", [(32, 16, 8), (24, 5, 8)])
def test_flash_backward_kernel_parity(causal, t, d, blk):
    """The Pallas dq/dk/dv kernels (multi-block grids, head-dim padding)
    against the dense reference VJP, with a non-trivial cotangent."""
    q, k, v = qkv(30, b=2, h=2, t=t, d=d)
    g = jax.random.normal(jax.random.key(31), q.shape)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal, blk, blk, True)

    def f_ref(q, k, v):
        return reference_attention(q, k, v, causal=causal)

    _, vjp_flash = jax.vjp(f_flash, q, k, v)
    _, vjp_ref = jax.vjp(f_ref, q, k, v)
    for gf, gr in zip(vjp_flash(g), vjp_ref(g)):
        np.testing.assert_allclose(gf, gr, atol=1e-4)


def test_flash_backward_is_pallas_not_recompute():
    """The VJP lowers to Pallas custom calls, not an XLA softmax
    recompute: the backward HLO must contain no `reduce`-based softmax
    normalizer outside custom calls — we assert on the jaxpr instead:
    every attention matmul in the bwd jaxpr lives inside a pallas_call."""
    q, k, v = qkv(32, b=1, h=1, t=16, d=8)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 8, 8, True))

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    prims = {e.primitive.name for e in jaxpr.jaxpr.eqns}
    # grad-of-flash should introduce pallas_call(s) and no lax.scan
    # (the blockwise recompute path would bring a scan in).
    flat = jaxpr.jaxpr.pretty_print(use_color=False)
    assert "pallas_call" in flat
    assert "scan" not in prims


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Sequence sharded over sp=8: ring result == dense attention on the
    unsharded sequence, including cross-device causal masking."""
    mesh = make_mesh(dp=1, sp=8)
    q, k, v = qkv(3, t=32)  # t_local = 4
    expected = reference_attention(q, k, v, causal=causal)

    def body(q, k, v):
        return ring_attention(q, k, v, "sp", 8, causal=causal)

    got = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(None, None, "sp"), P(None, None, "sp"), P(None, None, "sp")),
            out_specs=P(None, None, "sp"),
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_ring_attention_differentiable():
    mesh = make_mesh(dp=1, sp=8)
    q, k, v = qkv(4, b=1, h=1, t=16, d=8)

    def ring_loss(q, k, v):
        def body(q, k, v):
            return ring_attention(q, k, v, "sp", 8, causal=True)

        out = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(None, None, "sp"),) * 3,
            out_specs=P(None, None, "sp"),
            check_vma=False,
        )(q, k, v)
        return jnp.sum(out**2)

    def ref_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, ge in zip(g_ring, g_ref):
        np.testing.assert_allclose(gr, ge, atol=1e-4)


def _tiny_actor(obs_dim=5, act_dim=3, t=16):
    actor = SequenceActor(
        act_dim=act_dim, d_model=32, num_heads=2, num_layers=1, max_len=64
    )
    obs = jax.random.normal(jax.random.key(5), (2, t, obs_dim))
    params = actor.init(jax.random.key(6), obs, jax.random.key(7))
    return actor, params, obs


def test_sequence_actor_shapes():
    actor, params, obs = _tiny_actor()
    action, logp = actor.apply(params, obs, jax.random.key(8))
    assert action.shape == (2, 3)
    assert logp.shape == (2,)
    assert bool(jnp.all(jnp.abs(action) <= 1.0))
    assert bool(jnp.all(jnp.isfinite(logp)))


def test_sequence_trunk_is_causal():
    """Perturbing future observations must not change past positions."""
    actor, params, obs = _tiny_actor()
    h = actor.apply(params, obs, method=SequenceActor.trunk)
    obs2 = obs.at[:, -1].set(obs[:, -1] + 100.0)
    h2 = actor.apply(params, obs2, method=SequenceActor.trunk)
    np.testing.assert_allclose(h[:, :-1], h2[:, :-1], atol=1e-6)
    assert not np.allclose(h[:, -1], h2[:, -1])


def test_context_parallel_actor_matches_single_device():
    actor, params, obs = _tiny_actor(t=16)
    mesh = make_mesh(dp=1, sp=8)
    a_single, _ = actor.apply(params, obs, None, True)  # deterministic
    a_ring, _ = context_parallel_actor_step(
        actor, params, obs, None, mesh, deterministic=True
    )
    np.testing.assert_allclose(a_ring, a_single, atol=1e-5)


def test_context_parallel_actor_stochastic_logprob():
    actor, params, obs = _tiny_actor(t=16)
    mesh = make_mesh(dp=1, sp=8)
    action, logp = context_parallel_actor_step(
        actor, params, obs, jax.random.key(9), mesh
    )
    assert action.shape == (2, 3)
    assert bool(jnp.all(jnp.isfinite(logp)))


@pytest.mark.slow
def test_sequence_double_critic_shapes():
    critic = SequenceDoubleCritic(d_model=32, num_heads=2, num_layers=1, max_len=64)
    obs = jax.random.normal(jax.random.key(10), (4, 8, 5))
    act = jax.random.normal(jax.random.key(11), (4, 3))
    params = critic.init(jax.random.key(12), obs, act)
    qs = critic.apply(params, obs, act)
    assert qs.shape == (2, 4)
    assert bool(jnp.all(jnp.isfinite(qs)))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bf16_operands_match_reference(causal):
    """bf16 q/k/v through fwd AND bwd: the kernels keep operands in
    their storage dtype on the MXU (f32 accumulation; probability/ds
    tiles cast down for the second matmul), so the result must track a
    dense f32 reference within bf16 tolerance — pins the
    mixed-precision path the sequence stack uses under
    compute_dtype=bfloat16."""
    q32, k32, v32 = qkv(40, b=2, h=2, t=32, d=16)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q32, k32, v32))
    g = jax.random.normal(jax.random.key(41), q32.shape)

    expected = reference_attention(q32, k32, v32, causal=causal)
    got = flash_attention(q, k, v, causal, 8, 8, True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        got.astype(jnp.float32), expected, atol=3e-2, rtol=3e-2
    )

    _, vjp_flash = jax.vjp(
        lambda q, k, v: flash_attention(q, k, v, causal, 8, 8, True), q, k, v
    )
    _, vjp_ref = jax.vjp(
        lambda q, k, v: reference_attention(q, k, v, causal=causal),
        q32, k32, v32,
    )
    for gf, gr in zip(vjp_flash(g.astype(jnp.bfloat16)), vjp_ref(g)):
        assert gf.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            gf.astype(jnp.float32), gr, atol=6e-2, rtol=6e-2
        )


def test_flash_rejects_mixed_operand_dtypes():
    q, k, v = qkv(50, t=16, d=16)
    with pytest.raises(ValueError, match="share one dtype"):
        flash_attention(q, k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
                        False, 8, 8, True)


def test_flash_surface_has_no_offset_masking():
    """Pin the NaN-safety precondition of the guard-free flash kernels.

    The in-kernel softmax dropped its isneginf guards on the invariant
    that NO row can be fully masked: causal rows always see key 0, and
    the public surface has no q/k position offsets or mask argument
    that could break that (ops/attention.py _flash_kernel comments).
    Whoever extends flash_attention with offset-style masking (e.g. a
    ring-attention Pallas path — blockwise_attention has exactly those
    params and keeps its guards) must re-add the guards and retire this
    pin.
    """
    import inspect

    from torch_actor_critic_tpu.ops import attention

    forbidden = {"q_offset", "k_offset", "offset", "mask", "segment_ids"}
    assert not (set(inspect.signature(attention.flash_attention).parameters)
                & forbidden)
    # The guarded blockwise path (ring attention's building block) DOES
    # carry offsets — the asymmetry is the design, keep it visible.
    assert {"q_offset", "k_offset"} <= set(
        inspect.signature(attention.blockwise_attention).parameters
    )
