"""PixelPendulum-v0 — the committed pixel-learning task (VERDICT r3 #1).

Pins the honesty contract (the observation contains no scalar state:
pixels + previous action only; velocity is observable from the
two-rod-channel frame) and the env's protocol/registry wiring. The
learning-curve evidence itself lives in ``runs/pixelpend-*`` (generated
by ``scripts/evidence_run.py``); these tests keep the task honest and
runnable.
"""

import numpy as np
import pytest

from torch_actor_critic_tpu.core.types import MultiObservation
from torch_actor_critic_tpu.envs.pixel_pendulum import SIZE, PixelPendulum, render_rod
from torch_actor_critic_tpu.envs.wrappers import is_visual_env, make_env


def test_registry_and_visual_dispatch():
    env = make_env("PixelPendulum-v0", seed=0)
    assert isinstance(env, PixelPendulum)
    assert is_visual_env("PixelPendulum-v0")
    env.close()


def test_render_rod_is_angle_sensitive():
    a, b, c = render_rod(0.0), render_rod(1.5), render_rod(np.pi)
    for img in (a, b, c):
        assert img.dtype == np.uint8
        assert (img > 0).sum() > 10  # the rod is actually drawn
    assert (a != b).any() and (a != c).any() and (b != c).any()
    # theta and theta+2pi are the same physical pose, identical frame
    np.testing.assert_array_equal(render_rod(0.5), render_rod(0.5 + 2 * np.pi))


def test_observation_contains_no_scalar_state():
    """features carries ONLY the previous action — never angle or
    velocity; pixels are the only state channel."""
    env = PixelPendulum(seed=0)
    o = env.reset(seed=0)
    assert isinstance(o, MultiObservation)
    assert o.features.shape == (env.act_dim,)
    np.testing.assert_array_equal(o.features, 0.0)  # no action yet
    assert o.frame.shape == (SIZE, SIZE, 3) and o.frame.dtype == np.uint8
    # At reset there is no motion: all three rod channels coincide.
    np.testing.assert_array_equal(o.frame[..., 0], o.frame[..., 1])
    np.testing.assert_array_equal(o.frame[..., 1], o.frame[..., 2])

    a = np.array([1.7], np.float32)
    o2, r, term, trunc = env.step(a)
    np.testing.assert_array_equal(o2.features, a)  # exactly the action
    assert np.isfinite(r) and not term
    env.close()


def test_velocity_is_observable_from_one_frame():
    """Channels hold the rod at t-2, t-1 and t — once the pendulum
    moves, they differ (without this the task would be partially
    observed: velocity aliasing, not vision)."""
    env = PixelPendulum(seed=0)
    env.reset(seed=0)
    moved = False
    for _ in range(5):
        o, *_ = env.step(np.array([2.0], np.float32))
        moved = moved or (o.frame[..., 0] != o.frame[..., 1]).any()
    assert moved
    env.close()


def test_temporal_channel_order():
    """Channels are (t-2, t-1, t) — pinned against the renderer so a
    reversed or shifted history cannot ship silently (the velocity /
    trend signal depends on this ordering)."""
    env = PixelPendulum(seed=0)
    env.reset(seed=3)
    thetas = [env._theta()]
    a = np.array([1.0], np.float32)
    for t in range(4):
        o, *_ = env.step(a)
        thetas.append(env._theta())
        expected = [thetas[max(t - 1, 0)], thetas[t], thetas[t + 1]]
        for c, th in enumerate(expected):
            np.testing.assert_array_equal(o.frame[..., c], render_rod(th))
    env.close()


def test_seeded_resets_are_reproducible():
    e1, e2 = PixelPendulum(seed=0), PixelPendulum(seed=0)
    o1, o2 = e1.reset(seed=7), e2.reset(seed=7)
    np.testing.assert_array_equal(o1.frame, o2.frame)
    a = np.array([0.5], np.float32)
    (n1, r1, *_), (n2, r2, *_) = e1.step(a), e2.step(a)
    assert r1 == r2
    np.testing.assert_array_equal(n1.frame, n2.frame)
    e1.close()
    e2.close()


@pytest.mark.slow
def test_pixel_pendulum_trains_through_visual_stack():
    """End-to-end smoke at the evidence-run geometry (tiny budget):
    the product trainer consumes PixelPendulum through the visual
    model/replay stack and produces finite losses."""
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(
        epochs=1, steps_per_epoch=60, start_steps=20, update_after=20,
        update_every=20, batch_size=16, buffer_size=500, max_ep_len=200,
        filters=(16, 32), kernel_sizes=(4, 3), strides=(2, 2),
        cnn_dense_size=64, cnn_features=8, normalize_pixels=True,
    )
    tr = Trainer("PixelPendulum-v0", cfg, mesh=make_mesh(dp=1), seed=0)
    m = tr.train()
    assert int(tr.state.step) > 0
    assert np.isfinite(m["loss_q"]) and np.isfinite(m["loss_pi"])
    assert tr.buffer.data.states.frame.dtype == np.uint8
    tr.close()


@pytest.mark.slow
def test_cnn_extracts_pose_and_velocity_supervised():
    """Observability pin for the anti-aliased frames (the claim the
    pixel learning curves rest on): a SimpleCNN regression recovers
    (cos theta, sin theta, theta-delta) from a single 3-channel frame
    to ~1e-3 MSE against ~0.5 target variance. If this fails, the task
    is broken — no RL result on it means anything."""
    import jax
    import jax.numpy as jnp
    import optax

    from torch_actor_critic_tpu.models.visual import SimpleCNN

    rng = np.random.default_rng(0)

    def make_batch(n):
        th = rng.uniform(-np.pi, np.pi, n)
        thp = th - rng.uniform(-0.4, 0.4, n)
        frames = np.stack([
            np.stack([
                render_rod(float(p)),
                render_rod(float((p + b) / 2)),
                render_rod(float(b)),
            ], -1)
            for p, b in zip(thp, th)
        ])
        y = np.stack([np.cos(th), np.sin(th), th - thp], -1).astype(np.float32)
        return jnp.asarray(frames), jnp.asarray(y)

    net = SimpleCNN((16, 32), (4, 3), (2, 2), dense_size=128,
                    out_features=3, normalize_pixels=True)
    params = net.init(jax.random.key(0), jnp.zeros((1, SIZE, SIZE, 3), jnp.uint8))
    opt = optax.adam(3e-4)
    ost = opt.init(params)

    @jax.jit
    def step(params, ost, x, y):
        def loss(p):
            return jnp.mean((net.apply(p, x) - y) ** 2)

        l, g = jax.value_and_grad(loss)(params)
        u, ost2 = opt.update(g, ost)
        return optax.apply_updates(params, u), ost2, l

    x, y = make_batch(512)
    for i in range(400):
        j = rng.integers(0, 512, 64)
        params, ost, _ = step(params, ost, x[j], y[j])
    xv, yv = make_batch(128)
    mse = float(jnp.mean((net.apply(params, xv) - yv) ** 2))
    assert mse < 0.02, mse  # targets have variance ~0.5; probe hits ~1e-3


def test_balance_variant_starts_near_upright():
    """PixelPendulumBalance-v0: same physics/pixels contract, resets
    near upright (stabilization task — see the class docstring for the
    budget rationale vs swing-up)."""
    env = make_env("PixelPendulumBalance-v0", seed=0)
    assert is_visual_env("PixelPendulumBalance-v0")
    for ep in range(5):
        env.reset(seed=ep)
        assert abs(env._theta()) < 0.15 * np.pi + 1e-6
    # reproducible via the seeded generator
    env.reset(seed=3)
    t1 = env._theta()
    env.reset(seed=3)
    assert env._theta() == t1
    env.close()
