"""--sanitize runtime transfer sanitizers (docs/ANALYSIS.md "Runtime
sanitizers"): the off tier is pinned no-op parity (bitwise metric
stream, identical schema), the on tier is behavior-neutral on clean
paths and a HARD failure on injected implicit host<->device transfers,
on both planes (trainer burst/drain, serving forward)."""

import numpy as np
import pytest

import jax

from torch_actor_critic_tpu.parallel import make_mesh
from torch_actor_critic_tpu.sac.trainer import Trainer
from torch_actor_critic_tpu.utils.config import SACConfig

TINY = dict(
    hidden_sizes=(16, 16), batch_size=16, epochs=1, steps_per_epoch=90,
    start_steps=30, update_after=30, update_every=30, buffer_size=2000,
    max_ep_len=100, save_every=1000, sentinel=False,
)

OBS_DIM, ACT_DIM = 5, 2


def _train(tier, seed=11):
    tr = Trainer(
        "Pendulum-v1", SACConfig(**TINY, sanitize=tier),
        mesh=make_mesh(dp=1), seed=seed,
    )
    try:
        return tr.train()
    finally:
        tr.close()


def test_config_validates_tier():
    with pytest.raises(ValueError, match="sanitize"):
        SACConfig(sanitize="loud")
    assert SACConfig().sanitize == "off"


def test_off_tier_is_noop_parity_and_on_is_bitwise_clean():
    # Off (the default) is the historical dispatch path; on must be
    # bitwise-equal to it on a clean run AND add no metric keys —
    # the guard observes transfers, it never changes math.
    off = _train("off")
    on = _train("on")
    assert set(off) == set(on)
    for k in ("loss_q", "loss_pi", "reward"):
        assert off[k] == on[k], (k, off[k], on[k])
        assert np.isfinite(on[k])


def test_guard_trips_on_injected_host_chunk(monkeypatch):
    # The injected host read: the placed chunk left as raw numpy, so
    # the guarded burst dispatch sees an implicit host->device
    # transfer — a hard failure, not a silent per-window transfer tax.
    import torch_actor_critic_tpu.sac.trainer as trmod

    monkeypatch.setattr(
        trmod, "shard_chunk_from_local", lambda chunk, mesh, sp=1: chunk
    )
    tr = Trainer(
        "Pendulum-v1", SACConfig(**TINY, sanitize="on"),
        mesh=make_mesh(dp=1), seed=11,
    )
    try:
        with pytest.raises(Exception, match="(?i)transfer"):
            tr.train()
    finally:
        tr.close()


def _actor_and_params():
    from torch_actor_critic_tpu.models import Actor

    actor = Actor(act_dim=ACT_DIM, hidden_sizes=(16, 16))
    params = actor.init(
        jax.random.key(0), np.zeros((1, OBS_DIM), np.float32), None,
        deterministic=True, with_logprob=False,
    )
    return actor, params


def test_sanitized_engine_forward_clean_and_bitwise():
    from torch_actor_critic_tpu.serve.engine import PolicyEngine

    actor, params = _actor_and_params()
    spec = jax.ShapeDtypeStruct((OBS_DIM,), np.float32)
    params = jax.device_put(params)
    obs = np.linspace(-1, 1, 3 * OBS_DIM, dtype=np.float32).reshape(
        3, OBS_DIM
    )
    plain = PolicyEngine(actor, spec, max_batch=4).act(
        params, obs, deterministic=True
    )
    sane = PolicyEngine(actor, spec, max_batch=4, sanitize=True).act(
        params, obs, deterministic=True
    )
    np.testing.assert_array_equal(plain, sane)
    # Sampled path (explicit key placement) answers too.
    out = PolicyEngine(actor, spec, max_batch=4, sanitize=True).act(
        params, obs, key=jax.random.key(3), deterministic=False
    )
    assert out.shape == (3, ACT_DIM) and np.isfinite(out).all()


def test_sanitized_engine_trips_on_host_params():
    from torch_actor_critic_tpu.serve.engine import PolicyEngine

    actor, params = _actor_and_params()
    spec = jax.ShapeDtypeStruct((OBS_DIM,), np.float32)
    np_params = jax.tree_util.tree_map(np.asarray, params)
    engine = PolicyEngine(actor, spec, max_batch=4, sanitize=True)
    with pytest.raises(Exception, match="(?i)transfer"):
        engine.act(
            np_params, np.zeros((2, OBS_DIM), np.float32),
            deterministic=True,
        )


def test_registry_and_replicate_carry_sanitize():
    from torch_actor_critic_tpu.serve import ModelRegistry

    actor, params = _actor_and_params()
    spec = jax.ShapeDtypeStruct((OBS_DIM,), np.float32)
    reg = ModelRegistry(sanitize=True)
    reg.register(
        "default", actor, spec, params=jax.device_put(params),
        max_batch=4, warmup=False,
    )
    engine, _, _ = reg.acquire("default")
    assert engine.sanitize
    assert engine.replicate().sanitize
