"""Host env adapter contracts (envs/wrappers.py).

Focused on the reproducibility surface: ``reset(seed)`` must actually
reseed every env family (round-1 weak #5: dm_control envs silently
ignored it — the trainer's per-env reset seeds were no-ops).
"""

import numpy as np
import pytest

from torch_actor_critic_tpu.envs.wrappers import make_env


def test_gymnasium_reset_seed_deterministic():
    env = make_env("Pendulum-v1", seed=0)
    a = env.reset(seed=123)
    b = env.reset(seed=123)
    c = env.reset(seed=124)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    env.close()


def test_dm_control_reset_seed_deterministic():
    pytest.importorskip("dm_control")
    env = make_env("dm:cartpole:swingup", seed=0)
    a = env.reset(seed=123)
    b = env.reset(seed=123)
    c = env.reset(seed=124)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    # sample_action is reseeded too (warmup reproducibility).
    env.reset(seed=5)
    s1 = env.sample_action()
    env.reset(seed=5)
    s2 = env.sample_action()
    np.testing.assert_array_equal(s1, s2)


def test_dm_control_reset_without_seed_keeps_stream():
    """No seed -> episodes keep drawing from the existing stream (two
    consecutive unseeded resets of a stochastic-init task differ)."""
    pytest.importorskip("dm_control")
    env = make_env("dm:cartpole:swingup", seed=7)
    a = env.reset()
    b = env.reset()
    assert not np.array_equal(a, b)


def test_history_env_propagates_reset_seed():
    env = make_env("Pendulum-v1|history:4", seed=0)
    a = env.reset(seed=9)
    b = env.reset(seed=9)
    np.testing.assert_array_equal(a, b)
    assert a.shape[0] == 4
    env.close()
