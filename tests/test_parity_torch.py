"""Numeric parity against an independent PyTorch computation.

SURVEY.md §7's minimum-slice gate: "same weights -> same losses to fp
tolerance" against the PyTorch reference semantics. We copy Flax params
into plain functional torch code (written here, independently of the
reference's nn.Module classes) implementing the same math —
torch.distributions.Normal log-probs, the tanh correction, the Bellman
backup — and require agreement to fp32 tolerance.

The stochastic paths can't be compared bit-for-bit across RNGs, so
parity is pinned where it is deterministic: the actor's deterministic
forward (mode + log-prob at the mode, exactly what the reference
computes when ``deterministic=True``, ref ``networks/linear.py:43-51``),
the critic forward, and the Bellman backup arithmetic.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from torch_actor_critic_tpu.models import Actor, DoubleCritic  # noqa: E402

OBS_DIM, ACT_DIM = 11, 3
HIDDEN = (32, 16)
ACT_LIMIT = 2.0


def _dense_params(tree):
    """(kernel, bias) of a wrapped Dense module subtree (the single
    inner nn.Dense is named by its TP role: Dense_0/col/row)."""
    (inner,) = tree.values()
    return np.asarray(inner["kernel"]), np.asarray(inner["bias"])


def _torch_mlp(x, layer_params, relu_final):
    n = len(layer_params)
    for i, (w, b) in enumerate(layer_params):
        x = x @ torch.tensor(w) + torch.tensor(b)
        if relu_final or i < n - 1:
            x = torch.relu(x)
    return x


def test_actor_deterministic_forward_matches_torch():
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=HIDDEN, act_limit=ACT_LIMIT)
    obs = jax.random.normal(jax.random.key(1), (16, OBS_DIM))
    params = actor.init(jax.random.key(0), obs, jax.random.key(2))

    action_jax, logp_jax = actor.apply(
        params, obs, deterministic=True, with_logprob=True
    )

    p = params["params"]
    trunk = [
        _dense_params(p["MLP_0"][f"Dense_{i}"]) for i in range(len(HIDDEN))
    ]
    mu_w, mu_b = _dense_params(p["Dense_0"])
    ls_w, ls_b = _dense_params(p["Dense_1"])

    x = torch.tensor(np.asarray(obs))
    h = _torch_mlp(x, trunk, relu_final=True)
    mu = h @ torch.tensor(mu_w) + torch.tensor(mu_b)
    log_std = torch.clip(h @ torch.tensor(ls_w) + torch.tensor(ls_b), -20.0, 2.0)
    dist = torch.distributions.Normal(mu, torch.exp(log_std))
    u = mu  # deterministic mode
    action_t = torch.tanh(u) * ACT_LIMIT
    logp_t = dist.log_prob(u).sum(-1)
    logp_t = logp_t - (
        2.0 * (math.log(2.0) - u - torch.nn.functional.softplus(-2.0 * u))
    ).sum(-1)

    np.testing.assert_allclose(
        np.asarray(action_jax), action_t.numpy(), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(logp_jax), logp_t.numpy(), rtol=1e-4, atol=1e-5
    )


def test_double_critic_forward_matches_torch():
    critic = DoubleCritic(hidden_sizes=HIDDEN, num_qs=2)
    obs = jax.random.normal(jax.random.key(1), (16, OBS_DIM))
    act = jax.random.normal(jax.random.key(2), (16, ACT_DIM))
    params = critic.init(jax.random.key(0), obs, act)
    q_jax = np.asarray(critic.apply(params, obs, act))

    ens = params["params"]["ensemble"]["MLP_0"]
    x_in = torch.tensor(np.concatenate([np.asarray(obs), np.asarray(act)], -1))
    for member in range(2):
        layers = []
        for i in range(len(HIDDEN) + 1):
            w, b = _dense_params(
                jax.tree_util.tree_map(lambda a: a[member], ens[f"Dense_{i}"])
            )
            layers.append((w, b))
        q_t = _torch_mlp(x_in, layers, relu_final=False).squeeze(-1)
        np.testing.assert_allclose(
            q_jax[member], q_t.numpy(), rtol=1e-5, atol=1e-6
        )


def test_bellman_backup_matches_torch():
    """reward_scale*r + gamma*(1-d)*(min(q1t,q2t) - alpha*logp), as at
    ref sac/algorithm.py:60-67, over random inputs."""
    rng = np.random.default_rng(0)
    r = rng.normal(size=64).astype(np.float32)
    d = (rng.random(64) < 0.3).astype(np.float32)
    q1, q2 = rng.normal(size=(2, 64)).astype(np.float32)
    logp = rng.normal(size=64).astype(np.float32)
    alpha, gamma, scale = 0.2, 0.99, 1.5

    jb = scale * jnp.asarray(r) + gamma * (1 - jnp.asarray(d)) * (
        jnp.minimum(jnp.asarray(q1), jnp.asarray(q2)) - alpha * jnp.asarray(logp)
    )
    tb = scale * torch.tensor(r) + gamma * (1 - torch.tensor(d)) * (
        torch.minimum(torch.tensor(q1), torch.tensor(q2))
        - alpha * torch.tensor(logp)
    )
    np.testing.assert_allclose(np.asarray(jb), tb.numpy(), rtol=1e-6)


def test_adam_single_step_matches_torch():
    """optax.adam and torch.optim.Adam must produce the same first step
    given identical params/grads (lr 3e-4, torch defaults — the
    reference's optimizer config, ref main.py:93-95)."""
    import optax

    w0 = np.random.default_rng(1).normal(size=(8, 4)).astype(np.float32)
    g = np.random.default_rng(2).normal(size=(8, 4)).astype(np.float32)

    tx = optax.adam(3e-4)
    opt_state = tx.init(jnp.asarray(w0))
    updates, _ = tx.update(jnp.asarray(g), opt_state, jnp.asarray(w0))
    w_jax = np.asarray(optax.apply_updates(jnp.asarray(w0), updates))

    w_t = torch.tensor(w0.copy(), requires_grad=True)
    opt = torch.optim.Adam([w_t], lr=3e-4)
    w_t.grad = torch.tensor(g)
    opt.step()
    np.testing.assert_allclose(w_jax, w_t.detach().numpy(), rtol=1e-5, atol=1e-7)


def test_torch_visual_baseline_builds_and_updates():
    """The visual torch baseline (bench.py's BASELINE-config-5 ratio,
    baselines/torch_sac.py:build_torch_visual_sac) runs a full SAC
    gradient step at a tiny geometry: actor output contracts hold and
    the update mutates parameters. 36x36 is the smallest square frame
    the hardwired Atari conv geometry (8,4,3)/(4,2,1) admits."""
    from torch_actor_critic_tpu.baselines import build_torch_visual_sac

    feat, hw, c, act_dim, batch = 6, (36, 36), 3, 4, 5
    actor, update = build_torch_visual_sac(feat, hw, c, act_dim, hidden=(16, 16))
    frames = torch.rand(batch, c, *hw) * 255.0
    feats = torch.randn(batch, feat)
    with torch.no_grad():
        a, logp = actor(feats, frames)
    assert a.shape == (batch, act_dim) and logp.shape == (batch,)
    assert bool((a.abs() <= 1.0).all())
    before = [p.detach().clone() for p in actor.parameters()]
    update(
        feats, frames, torch.tanh(torch.randn(batch, act_dim)),
        torch.randn(batch), torch.randn(batch, feat),
        torch.rand(batch, c, *hw) * 255.0, torch.zeros(batch),
    )
    after = list(actor.parameters())
    assert any(
        not torch.equal(b, a.detach()) for b, a in zip(before, after)
    )
