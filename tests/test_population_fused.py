"""Population-fused on-device training (sac/ondevice.py).

The correctness contract has three layers, each pinned here:

1. **Bitwise member independence** — member ``i``'s epoch output is
   bitwise invariant to what the other population slots contain (the
   clone test): no leakage through replay sampling, optimizer state or
   PRNG streams, proven at full float precision.
2. **Stacked-single equivalence** — with PBT off, a population epoch is
   N single-learner :class:`OnDeviceLoop` epochs: warmup collection
   (envs, replay rings, PRNG streams) and loss streams are bitwise
   equal; parameter trajectories agree to float-accumulation order
   (vmap batches the backward matmuls, which XLA may legally
   reassociate — the same documented tolerance as
   ``tests/test_population.py``).
3. **On-device PBT** — per-member hyperparameters thread through
   ``TrainState.hyperparams`` (bitwise-neutral at default values), and
   the exploit/explore step copies winner params and perturbs loser
   hyperparameters entirely in-graph.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.core.types import Batch
from torch_actor_critic_tpu.buffer.replay import init_replay_buffer
from torch_actor_critic_tpu.envs.ondevice import PendulumJax
from torch_actor_critic_tpu.models import Actor, DoubleCritic
from torch_actor_critic_tpu.sac import SAC
from torch_actor_critic_tpu.sac.ondevice import (
    OnDeviceLoop,
    PBTState,
    PopulationOnDeviceLoop,
    train_population_on_device,
)
from torch_actor_critic_tpu.utils.config import SACConfig

OBS, ACT = 3, 1
N_ENVS = 4


def _sac(**over):
    cfg = SACConfig(hidden_sizes=(16, 16), batch_size=8, **over)
    return SAC(
        cfg,
        Actor(act_dim=ACT, hidden_sizes=cfg.hidden_sizes, act_limit=2.0),
        DoubleCritic(hidden_sizes=cfg.hidden_sizes),
        ACT,
    )


def _leaves(tree):
    """Comparable numpy leaves (typed PRNG keys as their uint32 data)."""
    return [
        np.asarray(
            jax.random.key_data(x)
            if jnp.issubdtype(x.dtype, jax.dtypes.prng_key)
            else x
        )
        for x in jax.tree_util.tree_leaves(tree)
    ]


def _assert_bitwise(a, b, what=""):
    for x, y in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(x, y, err_msg=what)


# ------------------------------------------------------- core equivalence


def test_warmup_epoch_bitwise_equals_stacked_single_runs():
    """PBT off, no updates: the vmapped collect path — env physics,
    replay pushes, PRNG streams, episode stats — is bitwise-equal to N
    separate single-learner OnDeviceLoop runs seeded with the member
    keys."""
    sac = _sac()
    pop = PopulationOnDeviceLoop(sac, PendulumJax, 3, n_envs=N_ENVS)
    root = jax.random.key(0)
    ts, buf, es, keys, _ = pop.init(root, buffer_capacity=512)
    ts, buf, es, keys, m = pop.epoch(
        ts, buf, es, keys, steps=20, update_every=10, warmup=True
    )
    single = OnDeviceLoop(sac, PendulumJax, n_envs=N_ENVS)
    member_keys = jax.random.split(root, 3)
    for i in range(3):
        sts, sbuf, ses, skey = single.init(member_keys[i], buffer_capacity=512)
        sts, sbuf, ses, skey, sm = single.epoch(
            sts, sbuf, ses, skey, steps=20, update_every=10, warmup=True
        )
        slice_i = lambda t: jax.tree_util.tree_map(lambda x: x[i], t)  # noqa: E731
        _assert_bitwise(slice_i(buf), sbuf, f"replay ring, member {i}")
        _assert_bitwise(slice_i(es), ses, f"env states, member {i}")
        _assert_bitwise(slice_i(ts), sts, f"train state, member {i}")
        _assert_bitwise(keys[i], skey, f"act key, member {i}")
        np.testing.assert_array_equal(
            np.asarray(m["episodes"])[i], np.asarray(sm["episodes"])
        )


def test_update_epoch_matches_stacked_single_runs():
    """PBT off, with gradient bursts: loss streams stay bitwise; the
    parameter trajectories agree to the documented float-reassociation
    tolerance (vmap batches the backward matmuls)."""
    sac = _sac()
    pop = PopulationOnDeviceLoop(sac, PendulumJax, 2, n_envs=N_ENVS)
    root = jax.random.key(1)
    ts, buf, es, keys, _ = pop.init(root, buffer_capacity=512)
    ts, buf, es, keys, _ = pop.epoch(
        ts, buf, es, keys, steps=10, update_every=10, warmup=True
    )
    ts, buf, es, keys, m = pop.epoch(ts, buf, es, keys, steps=20, update_every=10)
    assert int(np.asarray(ts.step)[0]) == 20

    single = OnDeviceLoop(sac, PendulumJax, n_envs=N_ENVS)
    member_keys = jax.random.split(root, 2)
    for i in range(2):
        sts, sbuf, ses, skey = single.init(member_keys[i], buffer_capacity=512)
        sts, sbuf, ses, skey, _ = single.epoch(
            sts, sbuf, ses, skey, steps=10, update_every=10, warmup=True
        )
        sts, sbuf, ses, skey, sm = single.epoch(
            sts, sbuf, ses, skey, steps=20, update_every=10
        )
        np.testing.assert_array_equal(
            np.asarray(m["loss_q"])[i], np.asarray(sm["loss_q"])
        )
        np.testing.assert_array_equal(
            np.asarray(m["loss_pi"])[i], np.asarray(sm["loss_pi"])
        )
        got = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda x: x[i], ts.actor_params)
        )
        want = jax.tree_util.tree_leaves(sts.actor_params)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=2e-5, atol=2e-6)
        # Ring cursors advanced identically.
        assert int(np.asarray(buf.size)[i]) == int(sbuf.size)
        assert int(np.asarray(buf.ptr)[i]) == int(sbuf.ptr)


def test_member_independence_is_bitwise():
    """The no-leakage pin, at FULL precision: run a diverse population,
    then rerun the SAME compiled epoch with every slot holding member
    i's state — slot i's complete output (params, optimizer moments,
    replay ring, env states, PRNG) must be bitwise identical. Any
    cross-member coupling whatsoever fails this."""
    sac = _sac()
    pop = PopulationOnDeviceLoop(sac, PendulumJax, 3, n_envs=N_ENVS)
    root = jax.random.key(2)

    def fresh():
        ts, buf, es, keys, _ = pop.init(root, buffer_capacity=512)
        return pop.epoch(
            ts, buf, es, keys, steps=10, update_every=10, warmup=True
        )[:4]

    ts, buf, es, keys = fresh()
    out_div = pop.epoch(ts, buf, es, keys, steps=10, update_every=10)

    for i in (0, 2):
        ts, buf, es, keys = fresh()
        rep = lambda x: jnp.repeat(x[i][None], 3, axis=0)  # noqa: E731
        clone = lambda t: jax.tree_util.tree_map(rep, t)  # noqa: E731
        out_clone = pop.epoch(
            clone(ts), clone(buf), clone(es), clone(keys),
            steps=10, update_every=10,
        )
        for got, want in zip(out_clone, out_div):
            _assert_bitwise(
                jax.tree_util.tree_map(lambda x: x[i], got),
                jax.tree_util.tree_map(lambda x: x[i], want),
                f"member {i} output depends on other slots",
            )


# -------------------------------------------------- hyperparam threading


def _chunk(key, window=10):
    ks = jax.random.split(key, 5)
    return Batch(
        states=jax.random.normal(ks[0], (window, OBS)),
        actions=jax.random.uniform(ks[1], (window, ACT), minval=-1, maxval=1),
        rewards=jax.random.normal(ks[2], (window,)),
        next_states=jax.random.normal(ks[3], (window, OBS)),
        done=jnp.zeros((window,)),
    )


def _burst(sac, state, n=3):
    buf = init_replay_buffer(64, jax.ShapeDtypeStruct((OBS,), jnp.float32), ACT)
    return sac.update_burst(state, buf, _chunk(jax.random.key(5)), n)


def test_default_hyperparams_are_bitwise_neutral():
    """TrainState.hyperparams at the configured values must reproduce
    the plain (hyperparams=None) program bit-for-bit — the dynamic-lr
    path replays optax.adam's exact op sequence."""
    sac = _sac()
    base = sac.init_state(jax.random.key(3), jnp.zeros((OBS,)))
    plain, _, mp = _burst(sac, base)
    hp, _, mh = _burst(sac, base.replace(hyperparams=sac.default_hyperparams()))
    _assert_bitwise(plain.actor_params, hp.actor_params)
    _assert_bitwise(plain.critic_params, hp.critic_params)
    _assert_bitwise(plain.pi_opt_state, hp.pi_opt_state)
    _assert_bitwise(plain.q_opt_state, hp.q_opt_state)
    np.testing.assert_array_equal(np.asarray(mp["loss_q"]), np.asarray(mh["loss_q"]))
    assert hp.hyperparams is not None  # carried through the scan


def test_hyperparams_actually_steer_the_update():
    sac = _sac()
    base = sac.init_state(jax.random.key(4), jnp.zeros((OBS,)))
    hp = sac.default_hyperparams()

    # actor_lr = 0 freezes the actor while the critic still learns
    frozen, _, _ = _burst(
        sac, base.replace(hyperparams={**hp, "actor_lr": jnp.float32(0.0)})
    )
    _assert_bitwise(frozen.actor_params, base.actor_params)
    assert not all(
        np.array_equal(a, b)
        for a, b in zip(_leaves(frozen.critic_params), _leaves(base.critic_params))
    )
    # critic_lr = 0 freezes critic (and its polyak target stays put)
    cfrozen, _, _ = _burst(
        sac, base.replace(hyperparams={**hp, "critic_lr": jnp.float32(0.0)})
    )
    _assert_bitwise(cfrozen.critic_params, base.critic_params)
    _assert_bitwise(cfrozen.target_critic_params, base.target_critic_params)
    # alpha is read from the hyperparams, not the config scalar
    _, _, m_lo = _burst(
        sac, base.replace(hyperparams={**hp, "alpha": jnp.float32(0.01)})
    )
    _, _, m_hi = _burst(
        sac, base.replace(hyperparams={**hp, "alpha": jnp.float32(5.0)})
    )
    assert float(m_lo["loss_pi"]) != float(m_hi["loss_pi"])


def test_td3_hyperparams_thread_through():
    from torch_actor_critic_tpu.models import DeterministicActor
    from torch_actor_critic_tpu.td3 import TD3

    cfg = SACConfig(algorithm="td3", hidden_sizes=(16, 16), batch_size=8)
    td3 = TD3(
        cfg,
        DeterministicActor(
            act_dim=ACT, hidden_sizes=cfg.hidden_sizes, act_limit=2.0,
            act_noise=cfg.act_noise,
        ),
        DoubleCritic(hidden_sizes=cfg.hidden_sizes),
        ACT,
    )
    base = td3.init_state(jax.random.key(6), jnp.zeros((OBS,)))
    hp = td3.default_hyperparams()
    assert set(hp) == {"actor_lr", "critic_lr", "target_noise"}
    plain, _, mp = _burst(td3, base)
    with_hp, _, mh = _burst(td3, base.replace(hyperparams=hp))
    _assert_bitwise(plain.actor_params, with_hp.actor_params)
    np.testing.assert_array_equal(
        np.asarray(mp["loss_q"]), np.asarray(mh["loss_q"])
    )
    _, _, m_noisy = _burst(
        td3, base.replace(hyperparams={**hp, "target_noise": jnp.float32(2.0)})
    )
    assert float(m_noisy["loss_q"]) != float(mp["loss_q"])


# ------------------------------------------------------------------- pbt


def test_pbt_step_copies_winner_and_perturbs_loser():
    cfg_over = dict(population=4, on_device=True, pbt_every=1,
                    pbt_quantile=0.25, pbt_perturb=1.25)
    sac = _sac(**cfg_over)
    pop = PopulationOnDeviceLoop(sac, PendulumJax, 4, n_envs=2, pbt=True)
    state, _, _, _, pbt_state = pop.init(jax.random.key(7), buffer_capacity=64)
    assert state.hyperparams is not None
    # Distinct EMAs: member 0 worst, member 1 best; quantile 0.25 of 4
    # exploits exactly one member from each end.
    pbt_state = PBTState(
        return_ema=jnp.array([0.0, 10.0, 5.0, 3.0]),
        ema_count=jnp.ones(4, jnp.int32),
        rng=jax.random.key(8),
    )
    new, ps, ev = pop.pbt_step(state, pbt_state)
    exploited = np.asarray(ev["exploited"])
    src = np.asarray(ev["src"])
    np.testing.assert_array_equal(exploited, [True, False, False, False])
    assert src[0] == 1 and (src[1:] == [1, 2, 3]).all()
    # Loser got the winner's params + optimizer state, bitwise.
    for tree in ("actor_params", "critic_params", "pi_opt_state", "q_opt_state"):
        _assert_bitwise(
            jax.tree_util.tree_map(lambda x: x[0], getattr(new, tree)),
            jax.tree_util.tree_map(lambda x: x[1], getattr(state, tree)),
            f"{tree} not copied from winner",
        )
        # Winners/middle members untouched.
        _assert_bitwise(
            jax.tree_util.tree_map(lambda x: x[1:], getattr(new, tree)),
            jax.tree_util.tree_map(lambda x: x[1:], getattr(state, tree)),
            f"{tree} of non-exploited members changed",
        )
    # PRNG streams are NOT copied: the clone must diverge from its source.
    _assert_bitwise(new.rng, state.rng, "member PRNG streams must be kept")
    # Hyperparams: loser = winner's value * perturb^±1; others unchanged.
    perturb = 1.25
    for k in state.hyperparams:
        old = np.asarray(state.hyperparams[k])
        got = np.asarray(new.hyperparams[k])
        ratio = got[0] / old[1]
        assert np.isclose(ratio, perturb) or np.isclose(ratio, 1 / perturb), (
            k, ratio,
        )
        np.testing.assert_array_equal(got[1:], old[1:])
    # Loser inherits the winner's EMA (competes as its new self).
    np.testing.assert_allclose(np.asarray(ps.return_ema), [10.0, 10.0, 5.0, 3.0])


def test_pbt_step_gated_until_every_member_ranked():
    sac = _sac(population=3, on_device=True, pbt_every=1)
    pop = PopulationOnDeviceLoop(sac, PendulumJax, 3, n_envs=2, pbt=True)
    state, _, _, _, _ = pop.init(jax.random.key(9), buffer_capacity=64)
    pbt_state = PBTState(
        return_ema=jnp.array([0.0, 5.0, 1.0]),
        ema_count=jnp.array([1, 0, 1], jnp.int32),  # member 1 unranked
        rng=jax.random.key(10),
    )
    new, ps, ev = pop.pbt_step(state, pbt_state)
    assert not bool(np.asarray(ev["ready"]))
    assert not np.asarray(ev["exploited"]).any()
    _assert_bitwise(new.actor_params, state.actor_params)


def test_update_ema_tracks_and_skips_empty_epochs():
    sac = _sac(population=2, on_device=True, pbt_every=1, pbt_ema=0.5)
    pop = PopulationOnDeviceLoop(sac, PendulumJax, 2, n_envs=2, pbt=True)
    ps = PBTState(
        return_ema=jnp.zeros(2), ema_count=jnp.zeros(2, jnp.int32),
        rng=jax.random.key(0),
    )
    # First contribution seeds the EMA outright.
    ps = pop.update_ema(
        ps, {"episodes": jnp.array([2.0, 0.0]),
             "reward": jnp.array([-100.0, jnp.nan])}
    )
    np.testing.assert_allclose(np.asarray(ps.return_ema), [-100.0, 0.0])
    np.testing.assert_array_equal(np.asarray(ps.ema_count), [1, 0])
    # Second blends at tau=0.5; the NaN no-episode member stays put.
    ps = pop.update_ema(
        ps, {"episodes": jnp.array([1.0, 0.0]),
             "reward": jnp.array([-50.0, jnp.nan])}
    )
    np.testing.assert_allclose(np.asarray(ps.return_ema), [-75.0, 0.0])


# ------------------------------------------- driver, checkpoint, export


def _driver_config(epochs):
    return SACConfig(
        population=3, on_device=True, on_device_envs=2,
        pbt_every=2, pbt_quantile=0.34, pbt_ema=0.5,
        hidden_sizes=(16, 16), batch_size=8,
        epochs=epochs, steps_per_epoch=20, update_every=10,
        start_steps=10, update_after=0, buffer_size=400,
        save_every=1, max_ep_len=100,
    )


@pytest.fixture(scope="module")
def resumed_vs_straight(tmp_path_factory):
    """Run A: 3 epochs straight. Run B: 2 epochs, then a fresh resumed
    driver for 1 more — the lossless-resume pin for populations."""
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer

    root = tmp_path_factory.mktemp("popckpt")
    m_straight = train_population_on_device(
        "Pendulum-v1", _driver_config(3),
        checkpointer=Checkpointer(root / "a"), seed=3,
    )
    train_population_on_device(
        "Pendulum-v1", _driver_config(2),
        checkpointer=Checkpointer(root / "b"), seed=3,
    )
    m_resumed = train_population_on_device(
        "Pendulum-v1", _driver_config(1),
        checkpointer=Checkpointer(root / "b"), seed=3,
    )
    return root, m_straight, m_resumed


def test_population_checkpoint_resume_is_bitwise(resumed_vs_straight):
    root, m_straight, m_resumed = resumed_vs_straight
    # Per-member loss/reward curves of the final epoch match EXACTLY —
    # the resumed run recomputed the identical epoch (stacked state +
    # member PRNG keys + hyperparams + env states all round-tripped).
    for k, v in m_straight.items():
        if k.endswith("_per_sec"):
            continue
        if isinstance(v, float) and np.isnan(v):
            assert np.isnan(m_resumed[k]), k
            continue
        assert m_resumed[k] == v, (k, v, m_resumed[k])
    # And the final checkpoints hold bitwise-identical actor params.
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer

    pa, meta_a = Checkpointer(root / "a").restore_actor_params()
    pb, meta_b = Checkpointer(root / "b").restore_actor_params()
    assert meta_a["epoch"] == meta_b["epoch"] == 2
    _assert_bitwise(pa, pb)


def test_member_curves_are_distinct(resumed_vs_straight):
    _, m_straight, _ = resumed_vs_straight
    losses = [m_straight[f"loss_q_m{i}"] for i in range(3)]
    assert all(np.isfinite(losses)), losses
    assert len(set(losses)) == 3, losses  # three real curves


def test_export_member_checkpoint_for_serving(resumed_vs_straight):
    from torch_actor_critic_tpu.utils.checkpoint import (
        Checkpointer,
        export_member_checkpoint,
    )

    root, _, _ = resumed_vs_straight
    member, epoch = export_member_checkpoint(root / "a", root / "export")
    pop_params, meta = Checkpointer(root / "a").restore_actor_params()
    best = (meta.get("pbt") or {}).get("return_ema")
    assert member == int(np.argmax(best))
    one, one_meta = Checkpointer(root / "export").restore_actor_params()
    _assert_bitwise(
        one, jax.tree_util.tree_map(lambda x: x[member], pop_params)
    )
    assert one_meta["exported_member"] == member
    cfg = SACConfig.from_json(one_meta["config"])
    assert cfg.population == 1 and cfg.pbt_every == 0


def test_cli_routes_population_fused_and_emits_pbt_events(tmp_path):
    """train.py --on-device --population N end to end: per-member
    metrics in metrics.jsonl, a schema-valid pbt telemetry event, and
    a --run resume."""
    from torch_actor_critic_tpu.train import main as train_main

    args = [
        "--environment", "Pendulum-v1",
        "--on-device", "true",
        "--population", "2",
        "--pbt-every", "1",
        "--pbt-quantile", "0.5",
        "--telemetry", "true",
        "--devices", "1",
        "--runs-root", str(tmp_path),
        "--epochs", "2",
        "--steps-per-epoch", "20",
        "--update-every", "10",
        "--start-steps", "10",
        "--update-after", "0",
        "--batch-size", "8",
        "--buffer-size", "400",
        "--hidden-sizes", "16,16",
        "--on-device-envs", "2",
        "--max-ep-len", "100",
    ]
    metrics = train_main(args)
    assert "loss_q_m0" in metrics and "loss_q_m1" in metrics
    run_dir = next((tmp_path / "Default").iterdir())
    events = [
        json.loads(line)
        for line in (run_dir / "telemetry.jsonl").read_text().splitlines()
    ]
    pbt = [e for e in events if e.get("type") == "pbt"]
    assert pbt, "no pbt telemetry events"
    for e in pbt:
        assert {"epoch", "exploited", "src", "return_ema",
                "hyperparams"} <= set(e)
        assert len(e["src"]) == 2
    # Resume through the CLI (config comes from the stored run params).
    resumed = train_main(["--run", run_dir.name, "--runs-root", str(tmp_path)])
    assert "loss_q_m0" in resumed


# ------------------------------------------------ per-member normalizer


def test_per_member_normalizer_members_are_independent():
    from torch_actor_critic_tpu.utils.normalize import PerMemberNormalizer

    norm = PerMemberNormalizer(2, 3)
    rng = np.random.default_rng(0)
    # Member 0 sees N(0,1); member 1 sees N(100, 10).
    for _ in range(200):
        batch = np.stack([
            rng.normal(0.0, 1.0, 3), rng.normal(100.0, 10.0, 3)
        ])
        out = norm.normalize(batch)
    assert out.shape == (2, 3)
    np.testing.assert_allclose(norm.mean[0], 0.0, atol=0.5)
    np.testing.assert_allclose(norm.mean[1], 100.0, atol=3.0)
    # Pooling would have landed both means near 50 — independence held.
    one = norm.normalize(np.full(3, 100.0), update=False, member=1)
    assert one.shape == (3,)
    assert np.all(np.abs(one) < 2.0)  # near member 1's own mean
    far = norm.normalize(np.full(3, 100.0), update=False, member=0)
    assert np.all(far > 50.0)  # way off member 0's distribution
    # state_dict round-trip.
    d = norm.state_dict()
    norm2 = PerMemberNormalizer(2, 3)
    norm2.load_state_dict(d)
    np.testing.assert_array_equal(norm2.mean, norm.mean)
    np.testing.assert_array_equal(norm2.count, norm.count)
    with pytest.raises(ValueError, match="member-aligned"):
        norm.normalize(np.zeros((5, 3)))


def test_population_trainer_accepts_normalization(tmp_path):
    """population > 1 + normalize_observations no longer raises: the
    host trainer builds a PerMemberNormalizer and trains."""
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.utils.normalize import PerMemberNormalizer

    cfg = SACConfig(
        population=2, normalize_observations=True,
        hidden_sizes=(16, 16), batch_size=8,
        epochs=1, steps_per_epoch=30, start_steps=10, update_after=10,
        update_every=10, buffer_size=300, max_ep_len=100,
    )
    tr = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=1), seed=0)
    try:
        assert isinstance(tr.normalizer, PerMemberNormalizer)
        metrics = tr.train()
        assert np.isfinite(metrics["loss_q"])
        # Both members contributed their own statistics.
        assert (tr.normalizer.count > 0).all()
        ev = tr.evaluate(episodes=1, deterministic=True, seed=5)
        assert len(ev["per_member"]) == 2
    finally:
        tr.close()


def test_split_member_metrics_layout():
    from torch_actor_critic_tpu.diagnostics import split_member_metrics

    out = split_member_metrics({
        "loss_q": np.array([1.0, 3.0]),
        "loss_q_max": np.array([2.0, 5.0]),
        "reward": np.array([np.nan, -10.0]),
        "episodes": np.array([0.0, 4.0]),
        "scalar": np.float32(7.0),
    })
    assert out["loss_q_m0"] == 1.0 and out["loss_q_m1"] == 3.0
    assert out["loss_q"] == 2.0          # default suffix -> mean
    assert out["loss_q_max"] == 5.0      # _max suffix -> max
    assert np.isnan(out["reward_m0"]) and out["reward_m1"] == -10.0
    assert out["reward"] == -10.0        # finite members only
    assert out["scalar"] == 7.0
