"""aot/ subsystem tests (docs/SERVING.md "Cold start & warm-start
bundles"): manifest derivation from the checked tables, warm-start
bundle build/verify/round-trip, loud rejection with counted fallback,
the pre-forked warm pool, and a learner restart riding the persistent
compilation cache. All CPU (conftest pins JAX_PLATFORMS=cpu).
"""

import json
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.analysis.reachability import ENTRY_POINTS
from torch_actor_critic_tpu.aot import (
    BundleMismatchError,
    ManifestError,
    WarmPool,
    build_bundle,
    bundled_entry_points,
    default_bundle_dir,
    entry_point_table,
    load_bundle,
    serve_programs,
)
from torch_actor_critic_tpu.aot.manifest import (
    program_filename,
    program_name,
)
from torch_actor_critic_tpu.diagnostics.watchdog import get_watchdog
from torch_actor_critic_tpu.models import Actor
from torch_actor_critic_tpu.serve import ModelRegistry
from torch_actor_critic_tpu.serve.engine import PolicyEngine

OBS_DIM, ACT_DIM = 17, 6


def make_actor_and_params(seed=0, hidden=(32, 32)):
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=hidden)
    params = actor.init(
        jax.random.key(seed), jnp.zeros((OBS_DIM,)), jax.random.key(1)
    )
    return actor, params


def flat_spec():
    return jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32)


# ---------------------------------------------------------------- manifest


def test_manifest_matches_entry_points_exactly():
    """No third list: the manifest's identity set IS the checked
    ENTRY_POINTS set, and every row carries an explicit bundleability
    verdict (the stale-bundle-manifest lint pins the literal)."""
    table = entry_point_table()
    assert set(table) == set(ENTRY_POINTS)
    assert all(isinstance(v, bool) for v in table.values())
    # The single-device serve forward is the one bundled identity;
    # train-plane programs ride the shared persistent cache instead.
    assert table["serve/forward"] is True
    assert bundled_entry_points() == ("serve/forward",)
    assert table["serve/sharded_forward"] is False
    assert table["train/update_burst"] is False


def test_manifest_raises_on_table_divergence(monkeypatch):
    """A jit entry point with no contract row (or vice versa) must fail
    the build loudly, not silently skip a program."""
    import torch_actor_critic_tpu.aot.manifest as manifest_mod

    monkeypatch.setattr(
        manifest_mod, "ENTRY_POINTS",
        dict(ENTRY_POINTS, **{"serve/new_thing": ("x.py", "f")}),
    )
    with pytest.raises(ManifestError, match="serve/new_thing"):
        manifest_mod.entry_point_table()


def test_program_naming():
    assert program_name("serve/forward", 4, True) == "serve/forward[b4].det"
    assert (
        program_name("serve/forward", 16, False)
        == "serve/forward[b16].sampled"
    )
    assert (
        program_filename("serve/forward[b4].det")
        == "serve__forward-b4.det.jexp"
    )


def test_serve_programs_cover_the_warmup_ladder():
    specs = serve_programs((2, 4))
    assert [s.name for s in specs] == [
        "serve/forward[b2].det", "serve/forward[b2].sampled",
        "serve/forward[b4].det", "serve/forward[b4].sampled",
    ]
    det_only = serve_programs((2, 4), deterministic_only=True)
    assert all(s.deterministic for s in det_only)
    assert len(det_only) == 2


# ------------------------------------------------------------------ bundle


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    """One bundle shared by the read-only bundle tests: a real
    build_bundle() run (engine warmup -> xla_cache + jax.export)."""
    root = tmp_path_factory.mktemp("aot") / "warm_start"
    actor, params = make_actor_and_params()
    bundle = build_bundle(
        root, actor, flat_spec(), params, max_batch=4,
    )
    return bundle, actor, params


def test_bundle_layout_and_manifest(built):
    bundle, _, _ = built
    manifest = json.loads((bundle.root / "MANIFEST.json").read_text())
    assert manifest["format"] == 1
    assert manifest["buckets"] == [2, 4]
    assert manifest["entry_points"] == entry_point_table()
    # The cache really was populated by the build-time warmup — the
    # mechanism behind live_compiles == 0 on a fresh worker.
    assert manifest["cache_entries"] > 0
    assert set(manifest["programs"]) == {
        s.name for s in serve_programs((2, 4))
    }
    bundle.check()  # same process, same fingerprint: must pass


def test_bundle_roundtrip_bitwise_identical_to_live_compile(built):
    """The serialized programs ARE the engine's programs: every
    (bucket, deterministic) export replays bitwise against the live
    jit forward it was exported from."""
    bundle, actor, params = built
    engine = PolicyEngine(actor, flat_spec(), max_batch=4)
    rng = np.random.default_rng(0)
    key = jax.random.key(0)
    key_data = jax.random.key_data(key)
    for spec in serve_programs(engine.buckets):
        obs = rng.standard_normal((spec.bucket, OBS_DIM)).astype(np.float32)
        exported = bundle.load_program(spec.name)
        if spec.deterministic:
            got = exported.call(params, obs)
            want = engine._fwd[True](params, obs)
        else:
            # The artifact takes raw uint32 key data (jax.export has no
            # dtype kind for typed keys) and re-wraps inside — bitwise
            # identical to the engine's typed-key program.
            got = exported.call(params, obs, key_data)
            want = engine._fwd[False](params, obs, key)
        got_leaves = jax.tree_util.tree_leaves(got)
        want_leaves = jax.tree_util.tree_leaves(want)
        assert len(got_leaves) == len(want_leaves)
        for g, w in zip(got_leaves, want_leaves):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_fingerprint_mismatch_rejected_loudly(built):
    bundle, _, _ = built
    stale = load_bundle(bundle.root)
    stale.manifest["fingerprint"]["jaxlib"] = "0.0.0-elsewhere"
    with pytest.raises(BundleMismatchError, match="jaxlib"):
        stale.check()


def test_aval_mismatch_rejected(built):
    """Model/obs drift since the build: the program verifies against
    the consumer's own call avals and refuses on any disagreement."""
    bundle, _, params = built
    name = "serve/forward[b2].det"
    wrong_obs = np.zeros((2, OBS_DIM + 1), np.float32)
    with pytest.raises(BundleMismatchError, match="aval mismatch"):
        bundle.verify_program(name, params, wrong_obs)
    with pytest.raises(BundleMismatchError, match="no program"):
        bundle.load_program("serve/forward[b999].det")


def test_bundle_armed_warmup_pays_zero_live_compiles(built):
    """The headline pin: a bundle-armed warmup classifies every compile
    as bundle-load (disk-read cost), and the first real act afterwards
    pays nothing live."""
    bundle, actor, params = built
    wd = get_watchdog().install()
    wd.reset()
    engine = PolicyEngine(actor, flat_spec(), max_batch=4)
    engine.warmup(params, bundle=bundle)
    engine.act(params, np.zeros((3, OBS_DIM), np.float32))
    stats = engine.compile_stats()
    assert stats["live_compiles"] == 0
    warmup_total = sum(b["warmup"] for b in stats["buckets"].values())
    assert warmup_total == 0
    assert stats["bundle_compiles"] == len(serve_programs(engine.buckets))
    assert stats["bundle_loaded"] is True
    snap = wd.snapshot()
    assert snap["bundle_hits"] == len(serve_programs(engine.buckets))
    assert snap["bundle_load_compiles"] > 0
    assert wd.live_compiles_for("serve/") == 0
    wd.assert_zero_live("serve/")


def test_registry_rejection_falls_back_and_counts(built, tmp_path):
    """A corrupted bundle must cost the cold start back, never the
    slot: registration falls back to a live warmup, the rejection is
    counted on the watchdog, and the slot serves correctly."""
    bundle, actor, params = built
    broken_root = tmp_path / "broken"
    shutil.copytree(bundle.root, broken_root)
    victim = json.loads(
        (broken_root / "MANIFEST.json").read_text()
    )["programs"]["serve/forward[b2].det"]["file"]
    (broken_root / "programs" / victim).write_bytes(b"not a program")
    broken = load_bundle(broken_root)

    wd = get_watchdog().install()
    wd.reset()
    reg = ModelRegistry()
    try:
        reg.register(
            "default", actor, flat_spec(), params=params, max_batch=4,
            bundle=broken,
        )
        snap = wd.snapshot()
        assert snap["bundle_rejected"] == 1
        assert any(
            "deserialize" in r for r in snap["bundle_reject_reasons"]
        )
        slots = reg.slots()
        assert slots["default"]["bundle_loaded"] is False
        engine, _, _ = reg.acquire("default")
        stats = engine.compile_stats()
        # Fallback really was a LIVE warmup — nothing bundle-tagged,
        # nothing charged to a request.
        assert stats["bundle_compiles"] == 0
        assert sum(b["warmup"] for b in stats["buckets"].values()) > 0
        assert stats["live_compiles"] == 0
        act = engine.act(params, np.zeros((2, OBS_DIM), np.float32))
        assert np.isfinite(act).all()
        assert engine.compile_stats()["live_compiles"] == 0
    finally:
        reg.close()


# --------------------------------------------------------------- warm pool


def test_warm_pool_draw_answers_first_act_with_zero_live(built):
    """The pool's contract: spawn() returns READY workers, so a draw
    is O(pop) and the drawn worker's first act pays zero live compiles
    (here the worker is an in-process bundle-armed engine; serve.py
    wraps the real subprocess launcher around the same pool)."""
    bundle, actor, params = built
    killed = []

    def spawn():
        engine = PolicyEngine(actor, flat_spec(), max_batch=4)
        engine.warmup(params, bundle=bundle)
        return engine, f"inproc://{id(engine)}"

    pool = WarmPool(spawn, lambda h: killed.append(h), size=2)
    try:
        worker = pool.draw(timeout=120)
        assert worker is not None
        engine = worker.handle
        engine.act(params, np.zeros((1, OBS_DIM), np.float32))
        stats = engine.compile_stats()
        assert stats["live_compiles"] == 0
        assert stats["bundle_loaded"] is True
        # The pool refills behind the draw.
        deadline_stats = None
        for _ in range(600):
            deadline_stats = pool.stats()
            if deadline_stats["ready"] >= 2:
                break
            import time

            time.sleep(0.05)
        assert deadline_stats["ready"] == 2, deadline_stats
        assert deadline_stats["drawn"] == 1
        assert deadline_stats["spawned"] >= 3
    finally:
        pool.shutdown()
    # Unclaimed spares are reaped on shutdown; the drawn one is ours.
    assert len(killed) == 2
    assert pool.draw(timeout=0.1) is None  # post-shutdown draws refuse


def test_warm_pool_zero_size_is_inert():
    pool = WarmPool(
        lambda: (_ for _ in ()).throw(AssertionError("spawned")),
        lambda h: None, size=0,
    )
    assert pool.draw() is None
    assert pool.stats()["spawned"] == 0
    pool.shutdown()
    pool.shutdown()  # idempotent


def test_warm_pool_counts_spawn_failures():
    attempts = []

    def flaky_spawn():
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("boom")
        return object(), "inproc://ok"

    pool = WarmPool(flaky_spawn, lambda h: None, size=1)
    try:
        assert pool.draw(timeout=120) is not None
        assert pool.stats()["spawn_failures"] == 1
    finally:
        pool.shutdown()


# ------------------------------------------- learner restart on the cache


def test_learner_restart_rides_cache_bitwise(tmp_path):
    """A restarted learner pointed at the run's persistent compilation
    cache re-jits from disk hits and produces a loss stream BITWISE
    identical to the cold-cache run; --emit-bundle drops the
    checkpoint-adjacent warm_start bundle at the first update epoch."""
    from torch_actor_critic_tpu.aot.cache import (
        disable_persistent_cache,
        enable_persistent_cache,
    )
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    tiny = dict(
        hidden_sizes=(16, 16), batch_size=16, epochs=2,
        steps_per_epoch=40, start_steps=10, update_after=10,
        update_every=10, buffer_size=500, max_ep_len=100, save_every=1,
    )
    cache_dir = str(tmp_path / "xla_cache")

    def run(sub, emit):
        losses = []
        cfg = SACConfig(**tiny, emit_bundle=emit)
        ckpt_dir = tmp_path / sub / "ckpts"
        tr = Trainer(
            "Pendulum-v1", cfg, mesh=make_mesh(dp=1),
            checkpointer=Checkpointer(str(ckpt_dir), retry_backoff_s=0.0),
            seed=7,
        )
        real_hook = tr._epoch_boundary_hook

        def hook(e, ok, saved, metrics, rec, _real=real_hook):
            _real(e, ok, saved, metrics, rec)
            losses.append(metrics["loss_q"])

        tr._epoch_boundary_hook = hook
        try:
            tr.train()
        finally:
            tr.close()
        return losses, ckpt_dir

    wd = get_watchdog().install()
    enable_persistent_cache(cache_dir)
    try:
        losses_a, ckpt_a = run("a", emit=True)
        # --emit-bundle: the bundle landed next to the checkpoint at
        # the first update epoch, cache populated by its own warmup.
        bundle = load_bundle(default_bundle_dir(ckpt_a))
        assert bundle.manifest["cache_entries"] > 0
        bundle.check()

        wd.reset()
        losses_b, _ = run("b", emit=False)
        snap = wd.snapshot()
    finally:
        disable_persistent_cache()

    assert losses_a and losses_a == losses_b  # bitwise on the stream
    # The restarted learner really did ride the cache, not re-derive
    # it: its jit dispatches resolved to persistent-cache disk hits.
    assert snap["cache_hits_total"] > 0
