"""Integration tests against the real dm_control wall-runner physics.

Mirror of the reference's only integration suite
(``tests/test_wall_runner_env.py``): reset/step shape+type contracts and
a render smoke test — plus the contract the reference hardcodes but
never asserts (168-dim features, ref ``wall_runner.py:21``).

The CMU humanoid takes ~15s to build; the fixture is module-scoped.
"""

import numpy as np
import pytest

pytest.importorskip("dm_control")

from torch_actor_critic_tpu.core.types import MultiObservation  # noqa: E402
from torch_actor_critic_tpu.envs.wall_runner import (  # noqa: E402
    ACT_DIM,
    FEATURE_DIM,
    FRAME_SHAPE,
    DeepMindWallRunner,
)


@pytest.fixture(scope="module")
def environment():
    try:
        return DeepMindWallRunner(seed=0)
    except RuntimeError as e:
        if "rendering backend" in str(e) or "OpenGL" in str(e):
            # The egocentric camera frame genuinely requires a GL stack
            # (EGL/OSMesa/GLFW); hosts without one cannot run this env
            # at all — skip rather than error (cf. conftest's
            # MUJOCO_GL=disabled default for the physics-only tests).
            pytest.skip(f"no OpenGL rendering backend: {e}")
        raise


def test_reset_contract(environment):
    obs = environment.reset()
    assert isinstance(obs, MultiObservation)
    assert obs.features.shape == (FEATURE_DIM,)
    assert obs.features.dtype == np.float32
    assert obs.frame.shape == FRAME_SHAPE
    assert obs.frame.dtype == np.uint8


def test_step_contract(environment):
    environment.reset()
    obs, reward, terminated, truncated = environment.step(
        environment.sample_action()
    )
    assert isinstance(obs, MultiObservation)
    assert obs.features.shape == (FEATURE_DIM,)
    assert isinstance(reward, float)
    assert isinstance(terminated, bool) and isinstance(truncated, bool)
    assert environment.act_dim == ACT_DIM == 56  # ref wall_runner.py:20


def test_render_does_not_crash(environment):
    environment.render()  # no-op, like the reference (wall_runner.py:61-62)
