"""Properties of the fused SAC update step.

The reference never tests its losses or train loop (SURVEY.md §4);
these pin down the semantics of one gradient step and the
push-then-scan update burst.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.buffer import init_replay_buffer, push
from torch_actor_critic_tpu.core.types import Batch
from torch_actor_critic_tpu.models import Actor, DoubleCritic
from torch_actor_critic_tpu.sac import SAC, losses
from torch_actor_critic_tpu.utils.config import SACConfig

OBS_DIM, ACT_DIM = 4, 2


def make_sac(**overrides):
    cfg = SACConfig(hidden_sizes=(32, 32), batch_size=8, **overrides)
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=cfg.hidden_sizes, act_limit=1.0)
    critic = DoubleCritic(hidden_sizes=cfg.hidden_sizes, num_qs=cfg.num_qs)
    return SAC(cfg, actor, critic, ACT_DIM)


def make_batch(key, n=8):
    ks = jax.random.split(key, 5)
    return Batch(
        states=jax.random.normal(ks[0], (n, OBS_DIM)),
        actions=jnp.tanh(jax.random.normal(ks[1], (n, ACT_DIM))),
        rewards=jax.random.normal(ks[2], (n,)),
        next_states=jax.random.normal(ks[3], (n, OBS_DIM)),
        done=(jax.random.uniform(ks[4], (n,)) < 0.2).astype(jnp.float32),
    )


@pytest.fixture
def sac_and_state():
    sac = make_sac()
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    return sac, state


def test_init_state_target_equals_critic(sac_and_state):
    _, state = sac_and_state
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, state.critic_params, state.target_critic_params
    )
    assert int(state.step) == 0


def test_update_is_pure_and_deterministic(sac_and_state):
    sac, state = sac_and_state
    batch = make_batch(jax.random.key(1))
    s1, m1 = sac.update(state, batch)
    s2, m2 = sac.update(state, batch)
    jax.tree_util.tree_map(np.testing.assert_array_equal, s1.actor_params, s2.actor_params)
    assert float(m1["loss_q"]) == float(m2["loss_q"])


def test_update_moves_params_and_polyak_target(sac_and_state):
    sac, state = sac_and_state
    batch = make_batch(jax.random.key(1))
    new_state, metrics = jax.jit(sac.update)(state, batch)

    # params moved
    assert not np.allclose(
        np.asarray(jax.tree_util.tree_leaves(new_state.actor_params)[0]),
        np.asarray(jax.tree_util.tree_leaves(state.actor_params)[0]),
    )
    # target = polyak * old_target + (1-polyak) * NEW critic (post-step),
    # matching reference update order (critic step, then polyak over the
    # stepped critic, sac/algorithm.py:276-278).
    p = sac.config.polyak
    expected = jax.tree_util.tree_map(
        lambda new_c, old_t: p * old_t + (1 - p) * new_c,
        new_state.critic_params,
        state.target_critic_params,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
        expected,
        new_state.target_critic_params,
    )
    for k in ("loss_q", "loss_pi", "q_mean", "logp_pi"):
        assert np.isfinite(float(metrics[k])), k
    assert int(new_state.step) == 1


def test_fixed_alpha_is_constant(sac_and_state):
    sac, state = sac_and_state
    batch = make_batch(jax.random.key(1))
    new_state, metrics = sac.update(state, batch)
    assert float(new_state.log_alpha) == float(state.log_alpha)
    np.testing.assert_allclose(float(metrics["alpha"]), 0.2, rtol=1e-6)


def test_learned_alpha_moves():
    sac = make_sac(learn_alpha=True)
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    batch = make_batch(jax.random.key(1))
    new_state, _ = sac.update(state, batch)
    assert float(new_state.log_alpha) != float(state.log_alpha)
    # target_entropy defaults to -act_dim
    assert sac.target_entropy == -float(ACT_DIM)


def test_critic_loss_matches_manual_computation(sac_and_state):
    sac, state = sac_and_state
    batch = make_batch(jax.random.key(1))
    key = jax.random.key(7)
    cfg = sac.config

    loss, _ = losses.critic_loss(
        state.critic_params,
        actor_apply=sac._actor_apply,
        critic_apply=sac._critic_apply,
        actor_params=state.actor_params,
        target_critic_params=state.target_critic_params,
        batch=batch,
        key=key,
        alpha=jnp.float32(cfg.alpha),
        gamma=cfg.gamma,
        reward_scale=cfg.reward_scale,
    )

    # Manual replication with the same key.
    a2, logp = sac.actor_def.apply(state.actor_params, batch.next_states, key)
    qt = sac.critic_def.apply(state.target_critic_params, batch.next_states, a2)
    backup = np.asarray(batch.rewards) + cfg.gamma * (
        1 - np.asarray(batch.done)
    ) * (np.min(np.asarray(qt), axis=0) - cfg.alpha * np.asarray(logp))
    q = np.asarray(sac.critic_def.apply(state.critic_params, batch.states, batch.actions))
    expected = sum(np.mean((q[i] - backup) ** 2) for i in range(2))
    np.testing.assert_allclose(float(loss), expected, rtol=1e-5)


def test_parity_pi_obs_flag_changes_loss():
    """parity_pi_obs=True must sample pi from next_states (ref quirk)."""
    sac_fixed = make_sac(parity_pi_obs=False)
    sac_parity = make_sac(parity_pi_obs=True)
    state = sac_fixed.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    batch = make_batch(jax.random.key(1))

    kwargs = dict(
        actor_apply=sac_fixed._actor_apply,
        critic_apply=sac_fixed._critic_apply,
        critic_params=state.critic_params,
        batch=batch,
        key=jax.random.key(2),
        alpha=jnp.float32(0.2),
    )
    l_fixed, _ = losses.actor_loss(state.actor_params, parity_pi_obs=False, **kwargs)
    l_parity, _ = losses.actor_loss(state.actor_params, parity_pi_obs=True, **kwargs)
    assert float(l_fixed) != float(l_parity)

    # With states == next_states the two must agree exactly.
    same_batch = batch.replace(next_states=batch.states)
    kwargs["batch"] = same_batch
    l1, _ = losses.actor_loss(state.actor_params, parity_pi_obs=False, **kwargs)
    l2, _ = losses.actor_loss(state.actor_params, parity_pi_obs=True, **kwargs)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


def test_update_burst_end_to_end(sac_and_state):
    sac, state = sac_and_state
    buf = init_replay_buffer(64, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM)
    buf = push(buf, make_batch(jax.random.key(5), n=32))

    chunk = make_batch(jax.random.key(6), n=10)
    burst = jax.jit(sac.update_burst, static_argnums=(3,), donate_argnums=(0, 1))
    state2, buf2, metrics = burst(state, buf, chunk, 5)
    assert int(state2.step) == 5
    assert int(buf2.size) == 42
    assert np.isfinite(float(metrics["loss_q"]))
    assert metrics["loss_q"].shape == ()  # averaged over the burst


def test_redq_wide_ensemble_updates():
    """num_qs=4 (REDQ-style): the vmapped ensemble generalizes past the
    reference's hardwired twin — wider min-clipping targets train with
    finite losses and a (4, B) Q surface."""
    sac = make_sac(num_qs=4)
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    batch = make_batch(jax.random.key(1))
    q = sac.critic_def.apply(state.critic_params, batch.states, batch.actions)
    assert q.shape == (4, 8)
    new_state, metrics = jax.jit(sac.update)(state, batch)
    assert np.isfinite(float(metrics["loss_q"]))
    assert np.isfinite(float(metrics["loss_pi"]))
    # All four members moved.
    for i in range(4):
        a = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda x: x[i], state.critic_params)
        )[0]
        b = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda x: x[i], new_state.critic_params)
        )[0]
        assert not np.allclose(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_update_burst_donates_buffer_in_hlo(sac_and_state):
    """Perf-regression guard: the fused burst's replay buffer MUST be
    donated (input-output aliased in the compiled HLO). Losing donation
    would silently deep-copy the multi-GB HBM buffer on every dispatch
    — the exact host<->device-free replay design the framework trades
    on (SURVEY.md §7; bench.py measures through this jit signature).

    Differential: the same burst is compiled with and without the
    buffer in donate_argnums, and the alias-count delta must cover the
    buffer's 7 leaves (5 Batch fields + ptr + size) — train-state
    donation alone cannot satisfy this, so a regression that drops
    ONLY the buffer from donation turns the test red."""
    sac, state = sac_and_state
    buf = init_replay_buffer(256, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM)
    buf = jax.jit(push, donate_argnums=(0,))(buf, make_batch(jax.random.key(2), 64))

    def alias_count(donate):
        hlo = (
            jax.jit(sac.update_burst, static_argnums=(3,), donate_argnums=donate)
            .lower(state, buf, make_batch(jax.random.key(3), 10), 5)
            .compile()
            .as_text()
        )
        return hlo.count("must-alias") + hlo.count("may-alias")

    with_buffer = alias_count((0, 1))
    state_only = alias_count((0,))
    assert with_buffer - state_only >= 7, (with_buffer, state_only)


def test_burst_unroll_auto_resolves_by_backend(monkeypatch):
    """Default burst_unroll=0 is 'auto': 5 on the TPU backend, 1
    elsewhere. Both branches are pinned by patching the backend probe
    (the property reads it at call time); explicit values pass through
    unchanged and negatives are rejected at construction."""
    assert SACConfig().burst_unroll == 0
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert SACConfig().resolved_burst_unroll == 1
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert SACConfig().resolved_burst_unroll == 5
    assert SACConfig(burst_unroll=3).resolved_burst_unroll == 3
    with pytest.raises(ValueError, match="burst_unroll"):
        SACConfig(burst_unroll=-1)


def test_update_burst_unroll_is_semantics_preserving():
    """burst_unroll is a pure scheduling knob: the unrolled scan must
    produce exactly the same learner state and metrics as unroll=1
    (including a length that does not divide by the unroll factor)."""
    results = []
    for unroll in (1, 4):
        sac = make_sac(burst_unroll=unroll)
        state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
        buf = init_replay_buffer(
            64, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM
        )
        buf = push(buf, make_batch(jax.random.key(5), n=32))
        chunk = make_batch(jax.random.key(6), n=10)
        st, _, m = jax.jit(sac.update_burst, static_argnums=(3,))(
            state, buf, chunk, 6
        )
        results.append((st, m))
    (st1, m1), (st4, m4) = results
    np.testing.assert_allclose(float(m1["loss_q"]), float(m4["loss_q"]), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(st1.actor_params),
        jax.tree_util.tree_leaves(st4.actor_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
