"""DrQ random-shift augmentation (ops/augment.py): the gated pixel-RL
stabilizer. Parity default is "none" — these tests pin both the parity
no-op and the shift semantics (content-preserving spatial jitter,
independent per example and per use, uint8 in/uint8 out)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.core.types import Batch, MultiObservation
from torch_actor_critic_tpu.ops.augment import augment_batch, random_shift


def _frames(key, b=4, h=16, w=16, c=3):
    return jax.random.randint(key, (b, h, w, c), 0, 256, dtype=jnp.uint8)


def test_random_shift_preserves_dtype_shape_and_histogram_center():
    f = _frames(jax.random.key(0))
    out = random_shift(f, jax.random.key(1), pad=2)
    assert out.shape == f.shape and out.dtype == jnp.uint8
    # Zero-offset crop must be representable: with pad p the offset
    # (p, p) reproduces the original exactly; check shift really moves
    # content for at least one example (offsets are uniform over 25
    # cells, so 4 identical crops have probability 25^-4).
    assert (np.asarray(out) != np.asarray(f)).any()


def test_random_shift_is_translation_not_distortion():
    """Interior pixels survive translation exactly: shifting an image
    with a distinctive interior block keeps the block's values."""
    f = np.zeros((1, 16, 16, 1), np.uint8)
    f[0, 6:10, 6:10, 0] = 200
    out = np.asarray(random_shift(jnp.asarray(f), jax.random.key(3), pad=2))
    # The 4x4 block moved by at most 2 px but kept its mass (edge
    # padding cannot clip an interior block under pad=2).
    assert out.sum() == f.sum()
    assert set(np.unique(out)) == {0, 200}


def test_independent_offsets_per_example_and_per_call():
    f = jnp.broadcast_to(
        _frames(jax.random.key(2), b=1), (8, 16, 16, 3)
    )  # identical examples
    out = np.asarray(random_shift(f, jax.random.key(4), pad=4))
    # With identical inputs, differing outputs prove per-example offsets.
    assert any(
        (out[i] != out[0]).any() for i in range(1, 8)
    )
    out2 = np.asarray(random_shift(f, jax.random.key(5), pad=4))
    assert (out2 != out).any()  # fresh draw per call


def test_random_shift_large_pad_edge_replicates():
    """pad >= frame//2: offsets can push the crop entirely into the
    edge-replicated band. Shapes/dtype hold, values stay a subset of
    the original frame's (replication invents no pixels), and the
    extreme offsets are reachable."""
    h = w = 16
    pad = h // 2  # 8 — offsets span [0, 16] on a 16px frame
    f = _frames(jax.random.key(0), b=16, h=h, w=w)
    out = random_shift(f, jax.random.key(1), pad=pad)
    assert out.shape == f.shape and out.dtype == jnp.uint8
    for i in range(16):
        assert set(np.unique(out[i])) <= set(np.unique(f[i]))
    # The fused pipeline's clipped-index gather must agree with the
    # pad+crop spelling at this extreme pad too (same key, same
    # offsets — ops/pixels pins pad=4; this is the pad >= frame//2
    # edge).
    from torch_actor_critic_tpu.ops.augment import shift_offsets
    from torch_actor_critic_tpu.ops.pixels import gather_frames_reference

    got = gather_frames_reference(
        f, jnp.arange(16, dtype=jnp.int32),
        offsets=shift_offsets(jax.random.key(1), 16, pad), pad=pad,
        out_dtype=jnp.float32,
    )
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(out).astype(np.float32)
    )


def test_random_shift_non_square_frames():
    f = jax.random.randint(jax.random.key(2), (5, 12, 20, 3), 0, 256,
                           dtype=jnp.uint8)
    out = random_shift(f, jax.random.key(3), pad=4)
    assert out.shape == f.shape and out.dtype == jnp.uint8
    assert (np.asarray(out) != np.asarray(f)).any()


def test_random_shift_deterministic_under_fixed_key():
    f = _frames(jax.random.key(4), b=6)
    a = random_shift(f, jax.random.key(5), pad=4)
    b = random_shift(f, jax.random.key(5), pad=4)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = random_shift(f, jax.random.key(6), pad=4)
    assert (np.asarray(c) != np.asarray(a)).any()


def _visual_batch(key, b=4):
    ks = jax.random.split(key, 4)
    mo = lambda k: MultiObservation(
        features=jax.random.normal(k, (b, 2)),
        frame=_frames(k, b=b),
    )
    return Batch(
        states=mo(ks[0]),
        actions=jnp.zeros((b, 1)),
        rewards=jnp.zeros((b,)),
        next_states=mo(ks[1]),
        done=jnp.zeros((b,)),
    )


def test_augment_batch_none_is_identity_and_flat_is_passthrough():
    b = _visual_batch(jax.random.key(0))
    out = augment_batch(b, jax.random.key(1), "none")
    assert out is b
    flat = Batch(
        states=jnp.zeros((4, 3)), actions=jnp.zeros((4, 1)),
        rewards=jnp.zeros((4,)), next_states=jnp.zeros((4, 3)),
        done=jnp.zeros((4,)),
    )
    assert augment_batch(flat, jax.random.key(1), "shift") is flat


def test_augment_batch_shift_touches_only_frames():
    b = _visual_batch(jax.random.key(0))
    out = augment_batch(b, jax.random.key(1), "shift")
    np.testing.assert_array_equal(out.states.features, b.states.features)
    np.testing.assert_array_equal(out.actions, b.actions)
    assert (np.asarray(out.states.frame) != np.asarray(b.states.frame)).any()
    # states and next_states draw independent offsets
    assert (
        np.asarray(out.states.frame) != np.asarray(out.next_states.frame)
    ).any()


def test_augment_batch_unknown_mode_fails():
    with pytest.raises(ValueError, match="frame_augment"):
        augment_batch(_visual_batch(jax.random.key(0)), jax.random.key(1), "flip")


def test_visual_update_with_shift_augmentation():
    """The full SAC visual update runs with frame_augment=shift inside
    jit (static shapes, dynamic_slice crops) and yields finite losses."""
    from torch_actor_critic_tpu.sac.trainer import build_models, make_learner
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(
        hidden_sizes=(16, 16), batch_size=4,
        filters=(8,), kernel_sizes=(4,), strides=(2,),
        cnn_dense_size=16, cnn_features=4, normalize_pixels=True,
        frame_augment="shift",
    )

    class Spec:
        obs_spec = MultiObservation(
            features=jax.ShapeDtypeStruct((2,), jnp.float32),
            frame=jax.ShapeDtypeStruct((16, 16, 3), jnp.uint8),
        )
        act_dim = 1
        act_limit = 1.0

    actor, critic = build_models(cfg, Spec)
    sac = make_learner(cfg, actor, critic, 1)
    zero = MultiObservation(
        features=jnp.zeros((2,)), frame=jnp.zeros((16, 16, 3), jnp.uint8)
    )
    state = sac.init_state(jax.random.key(0), zero)
    batch = _visual_batch(jax.random.key(1))
    state, m = jax.jit(lambda s, b: sac.update(s, b))(state, batch)
    assert np.isfinite(float(m["loss_q"]))
    assert np.isfinite(float(m["loss_pi"]))


def test_frame_augment_validation_fails_at_construction():
    """Fail-at-construction policy: bad modes die in SACConfig; a
    visual-only augmentation requested for flat observations dies in
    build_models — never a silent no-op mid-run."""
    from torch_actor_critic_tpu.sac.trainer import build_models
    from torch_actor_critic_tpu.utils.config import SACConfig

    with pytest.raises(ValueError, match="frame_augment"):
        SACConfig(frame_augment="drq")
    with pytest.raises(ValueError, match="augment_pad"):
        SACConfig(frame_augment="shift", augment_pad=0)

    class FlatSpec:
        obs_spec = jax.ShapeDtypeStruct((3,), jnp.float32)
        act_dim = 1
        act_limit = 1.0

    with pytest.raises(ValueError, match="visual"):
        build_models(SACConfig(frame_augment="shift"), FlatSpec)


def test_augment_none_keeps_historical_rng_stream():
    """'none' is parity: the update's PRNG split count must not change
    with the augmentation feature's existence, so resumed checkpoints
    and recorded evidence runs replay identically."""
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(hidden_sizes=(8, 8), batch_size=4)
    sac = SAC(cfg, Actor(act_dim=1, hidden_sizes=(8, 8)),
              DoubleCritic(hidden_sizes=(8, 8)), 1)
    state = sac.init_state(jax.random.key(0), jnp.zeros((3,)))
    batch = Batch(
        states=jax.random.normal(jax.random.key(1), (4, 3)),
        actions=jnp.zeros((4, 1)),
        rewards=jnp.zeros((4,)),
        next_states=jax.random.normal(jax.random.key(2), (4, 3)),
        done=jnp.zeros((4,)),
    )
    _, m = jax.jit(lambda s, b: sac.update(s, b))(state, batch)
    # The exact key_q/key_pi derivation pre-dates frame_augment: a
    # 3-way split of the state rng. Recompute it independently.
    _, key_q, key_pi = jax.random.split(state.rng, 3)
    del key_q, key_pi  # derivation must not raise; stream pinned below
    # Stream pin: rng advanced exactly one 3-way split.
    new_rng = jax.random.split(state.rng, 3)[0]
    state2, _ = jax.jit(lambda s, b: sac.update(s, b))(state, batch)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(state2.rng)),
        np.asarray(jax.random.key_data(new_rng)),
    )
    assert np.isfinite(float(m["loss_q"]))
