"""The named-mesh GSPMD substrate (PR 8): parity with the retired
shard_map path, the un-gated dp+tp/fsdp hybrid, size-thresholded fsdp
parameter sharding, the member-sharded fused population, named-axis
skew collectives, and per-device cost attribution — all on the forced
8-device CPU mesh (conftest.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from torch_actor_critic_tpu.core.types import Batch
from torch_actor_critic_tpu.models import Actor, DoubleCritic
from torch_actor_critic_tpu.parallel import (
    DataParallelSAC,
    init_sharded_buffer,
    make_mesh,
    shard_chunk,
)
from torch_actor_critic_tpu.sac import SAC
from torch_actor_critic_tpu.utils.config import SACConfig

OBS_DIM, ACT_DIM = 4, 2


def make_sac(**overrides):
    cfg = SACConfig(hidden_sizes=(32, 32), batch_size=8, **overrides)
    return SAC(
        cfg,
        Actor(act_dim=ACT_DIM, hidden_sizes=cfg.hidden_sizes),
        DoubleCritic(hidden_sizes=cfg.hidden_sizes),
        ACT_DIM,
    )


def make_chunk(key, n_dev, per_dev):
    ks = jax.random.split(key, 5)
    shape = (n_dev, per_dev)
    return Batch(
        states=jax.random.normal(ks[0], shape + (OBS_DIM,)),
        actions=jnp.tanh(jax.random.normal(ks[1], shape + (ACT_DIM,))),
        rewards=jax.random.normal(ks[2], shape),
        next_states=jax.random.normal(ks[3], shape + (OBS_DIM,)),
        done=jnp.zeros(shape),
    )


def _dp_inputs(dp, seed_buf=128, n_updates_chunk=10):
    state = dp.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    buf = init_sharded_buffer(
        seed_buf, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM,
        dp.mesh,
    )
    n_dev = dp.n_devices
    warm = shard_chunk(make_chunk(jax.random.key(1), n_dev, 32), dp.mesh)
    chunk = shard_chunk(
        make_chunk(jax.random.key(2), n_dev, n_updates_chunk), dp.mesh
    )
    return state, buf, warm, chunk


# ------------------------------------------------------- substrate parity


def test_gspmd_burst_matches_legacy_shard_map_burst():
    """THE substrate-parity pin: one update burst through the retired
    ``compat.shard_map`` path and through the new jit-with-sharding
    path, same 2-device mesh, same inputs — params, opt state and
    metrics must agree. Proves the rebuild is a pure substrate swap:
    identical per-device key streams and math, only the mapping
    machinery changed (on CPU the two even agree bitwise; the pin is
    allclose so TPU reduction-order freedom can't break it)."""
    from torch_actor_critic_tpu.parallel import dp as dp_mod
    from torch_actor_critic_tpu.parallel.compat import shard_map

    sac = make_sac()
    mesh = make_mesh(dp=2, devices=jax.devices()[:2])
    dp = DataParallelSAC(sac, mesh)
    num_updates = 3

    def legacy_burst(state, buffer, chunk):
        """The pre-PR-8 manual body, verbatim semantics: strip the
        device axis, fold ``axis_index('dp')`` into the rng, run the
        shared burst with named-axis pmean, restore a replicated rng."""
        buf_specs = dp_mod._buffer_specs(buffer, 1)
        chunk_specs = dp_mod._batch_specs(chunk, 1)

        def body(state, buffer, chunk):
            buffer = jax.tree_util.tree_map(lambda x: x[0], buffer)
            chunk = jax.tree_util.tree_map(lambda x: x[0], chunk)
            dev = jax.lax.axis_index("dp")
            local = state.replace(rng=jax.random.fold_in(state.rng, dev))
            local, buffer, metrics = sac.update_burst(
                local, buffer, chunk, num_updates, axis_name="dp"
            )
            state_out = local.replace(
                rng=jax.random.fold_in(state.rng, jnp.uint32(0xB0057))
            )
            metrics = jax.lax.pmean(metrics, "dp")
            buffer = jax.tree_util.tree_map(lambda x: x[None], buffer)
            return state_out, buffer, metrics

        return jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(P(), buf_specs, chunk_specs),
                out_specs=(P(), buf_specs, P()),
                axis_names={"dp"},
                check_vma=False,
            )
        )(state, buffer, chunk)

    state, buf, warm, chunk = _dp_inputs(dp)
    s_old, b_old, m_old = legacy_burst(state, buf, warm)
    s_old, b_old, m_old = legacy_burst(s_old, b_old, chunk)

    state, buf, warm, chunk = _dp_inputs(dp)
    s_new, b_new, m_new = dp.update_burst(state, buf, warm, num_updates)
    s_new, b_new, m_new = dp.update_burst(s_new, b_new, chunk, num_updates)

    assert int(s_new.step) == int(s_old.step) == 2 * num_updates
    for key in m_old:
        np.testing.assert_allclose(
            np.asarray(m_new[key]), np.asarray(m_old[key]),
            rtol=1e-6, atol=1e-7, err_msg=key,
        )
    for group in ("actor_params", "critic_params", "target_critic_params",
                  "pi_opt_state", "q_opt_state"):
        for (path, a), b in zip(
            jax.tree_util.tree_flatten_with_path(getattr(s_new, group))[0],
            jax.tree_util.tree_leaves(getattr(s_old, group)),
        ):
            name = group + "/".join(
                str(getattr(p, "key", p)) for p in path
            )
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7,
                err_msg=name,
            )
    # Replay rings too: the push path swapped substrate as well.
    np.testing.assert_array_equal(
        np.asarray(b_new.size), np.asarray(b_old.size)
    )
    np.testing.assert_allclose(
        np.asarray(b_new.data.states), np.asarray(b_old.data.states),
        atol=0,
    )


def test_dp_burst_no_shard_map_on_hot_path():
    """The acceptance pin, promoted from a source-regex check to the
    tac-lint ``shard-map-hot-path`` rule (docs/ANALYSIS.md): any
    ``shard_map`` reference outside ``parallel/context.py`` +
    ``parallel/compat.py`` must sit in the rule's checked allowlist
    (the ``parallel/__init__`` re-export and the manual-by-nature sp
    ring burst), and every allowlist entry must still match real code
    (``stale-allowlist``). Zero findings over the whole package means
    the allowlist is the single source of truth for where manual
    mapping is allowed to live."""
    import pathlib

    from torch_actor_critic_tpu.analysis import lint_paths

    pkg = pathlib.Path(
        __import__("torch_actor_critic_tpu").__file__
    ).parent
    findings = [
        f for f in lint_paths([str(pkg)])
        if f.rule in ("shard-map-hot-path", "stale-allowlist")
    ]
    assert findings == [], "\n".join(f.format() for f in findings)


# ----------------------------------------------------- hybrid, no gate


def test_dp_fsdp_hybrid_runs_without_version_gate():
    """(dp=2, fsdp=2) with the size threshold forced to 0: parameters
    really shard over fsdp, the burst compiles and runs under plain
    auto partitioning on the installed jax (no ``hasattr(jax,
    'shard_map')`` gate anywhere), and the update equals the
    all-replicated (fsdp=1) burst — fsdp changes layout, not math."""
    assert not hasattr(jax, "shard_map")  # the gated jax: still works

    def run(fsdp):
        sac = make_sac()
        dp = DataParallelSAC(
            sac, make_mesh(dp=2, fsdp=fsdp, devices=jax.devices()[:2 * fsdp]),
            fsdp_min_bytes=0,
        )
        state, buf, warm, chunk = _dp_inputs(dp)
        if fsdp > 1:
            kern = state.actor_params["params"]["MLP_0"]["Dense_0"]["col"][
                "kernel"
            ]
            assert "fsdp" in (kern.sharding.spec or ())
            assert not kern.sharding.is_fully_replicated
        state, buf, _ = dp.update_burst(state, buf, warm, 2)
        state, buf, metrics = dp.update_burst(state, buf, chunk, 2)
        return state, metrics

    s_f, m_f = run(fsdp=2)
    s_r, m_r = run(fsdp=1)
    np.testing.assert_allclose(
        float(m_f["loss_q"]), float(m_r["loss_q"]), rtol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(s_f.critic_params),
        jax.tree_util.tree_leaves(s_r.critic_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


# ------------------------------------------------- fsdp sharding specs


def test_fsdp_spec_size_threshold_and_dim_choice():
    from torch_actor_critic_tpu.parallel.sharding import fsdp_spec

    big = jnp.zeros((128, 64))          # 32 KiB
    assert fsdp_spec(big, fsdp=4, min_bytes=0) == P("fsdp")
    # Largest divisible dim wins; dim 0 (96) > dim 1 (64) here.
    assert fsdp_spec(jnp.zeros((96, 64)), 4, 0) == P("fsdp")
    # dim 0 indivisible -> falls to the next divisible dim.
    assert fsdp_spec(jnp.zeros((97, 64)), 4, 0) == P(None, "fsdp")
    # Below threshold -> replicated.
    assert fsdp_spec(big, 4, big.nbytes + 1) == P()
    # Scalars / 1-D / fully indivisible -> replicated.
    assert fsdp_spec(jnp.zeros(()), 4, 0) == P()
    assert fsdp_spec(jnp.zeros((128,)), 4, 0) == P()
    assert fsdp_spec(jnp.zeros((3, 5)), 4, 0) == P()
    # fsdp=1 mesh -> replicated regardless of size.
    assert fsdp_spec(big, 1, 0) == P()


def test_fsdp_composes_with_tp_on_disjoint_dims():
    """A tp-taken dimension is skipped: fsdp lands on the largest
    remaining divisible dim, so the two families never collide."""
    from torch_actor_critic_tpu.parallel.sharding import fsdp_spec

    leaf = jnp.zeros((64, 32))
    assert fsdp_spec(leaf, 2, 0, taken=P(None, "tp")) == P("fsdp", "tp")
    assert fsdp_spec(leaf, 2, 0, taken=P("tp", None)) == P("tp", "fsdp")
    # Everything taken -> the tp spec passes through.
    assert fsdp_spec(jnp.zeros((64,)), 2, 0, taken=P("tp")) == P("tp")


def test_param_specs_replicate_scalars_and_small_arrays():
    """The scaling-book contract on a real model tree: scalars (step,
    log_alpha) and small arrays replicate, every spec on a trivial
    mesh is P()."""
    from torch_actor_critic_tpu.parallel.sharding import param_specs

    sac = make_sac()
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    trivial = make_mesh(dp=8)
    specs = jax.tree_util.tree_leaves(
        param_specs(state, trivial),
        is_leaf=lambda s: isinstance(s, P),
    )
    assert all(s == P() for s in specs)
    sharded = param_specs(
        state, make_mesh(dp=2, fsdp=4), min_bytes=0
    )
    assert sharded.log_alpha == P()
    assert sharded.step == P()
    kernel_specs = [
        s
        for path, s in jax.tree_util.tree_flatten_with_path(
            sharded.critic_params,
            is_leaf=lambda s: isinstance(s, P),
        )[0]
        if "kernel" in "/".join(str(getattr(p, "key", p)) for p in path)
    ]
    assert any("fsdp" in (s or ()) for s in kernel_specs)


# ------------------------------------------- named-axis skew collectives


def test_replica_skew_under_vmap_named_axis():
    """The dp-skew reductions read the SAME named axis whether the
    substrate is manual or a GSPMD vmap axis: pmax-pmin over
    ``axis_name='dp'`` inside jit-with-sharding equals the known
    spread."""
    from jax.sharding import NamedSharding
    from torch_actor_critic_tpu.diagnostics.ingraph import replica_skew

    mesh = make_mesh(dp=4, devices=jax.devices()[:4])

    def per_dev(v):
        skew = replica_skew({"diag/param_norm": v}, ("diag/param_norm",), "dp")
        return skew["diag/param_norm_skew"]

    def f(x):
        return jax.vmap(per_dev, axis_name="dp")(x)[0]

    xs = jax.device_put(
        jnp.asarray([0.0, 1.0, 2.0, 3.0]), NamedSharding(mesh, P("dp"))
    )
    out = jax.jit(
        f, in_shardings=NamedSharding(mesh, P("dp")),
        out_shardings=NamedSharding(mesh, P()),
    )(xs)
    assert float(out) == 3.0


def test_dp_skew_metrics_via_gspmd_burst_forced_devices():
    """Forced 4-device run of the NEW burst with diagnostics on: the
    desync canary still reads exactly 0.0 (pmean'd grads keep the
    per-device replicas bit-identical under the vmap substrate too)
    and per-shard grad skew is a real positive spread."""
    sac = make_sac(diagnostics="light")
    dp = DataParallelSAC(sac, make_mesh(dp=4, devices=jax.devices()[:4]))
    state, buf, warm, chunk = _dp_inputs(dp)
    _, _, m = dp.update_burst(state, buf, warm, 4)
    assert float(m["diag/param_norm_skew"]) == 0.0
    assert float(m["diag/grad_norm_q_skew"]) > 0.0
    assert float(m["diag/grad_norm_pi_skew"]) > 0.0


# ------------------------------------------- member-sharded population


def _pop_loop(mesh, n_members=8, pbt=True):
    from torch_actor_critic_tpu.envs.ondevice import PendulumJax
    from torch_actor_critic_tpu.sac.ondevice import PopulationOnDeviceLoop

    cfg = SACConfig(hidden_sizes=(16, 16), batch_size=8)
    sac = SAC(
        cfg,
        Actor(act_dim=1, hidden_sizes=cfg.hidden_sizes, act_limit=2.0),
        DoubleCritic(hidden_sizes=cfg.hidden_sizes),
        1,
    )
    return PopulationOnDeviceLoop(
        sac, PendulumJax, n_members=n_members, n_envs=2, pbt=pbt, mesh=mesh
    )


def test_population_member_axis_sharded_over_dp():
    """``--population 8`` on a dp=4 mesh: every member-stacked leaf —
    params, optimizer state, replay rings, env states, PRNG streams —
    spreads P('dp') across the 4 devices (2 members each), the epoch
    runs, per-member metrics stay distinct, and the layout survives
    the dispatch (donated buffers keep their sharding)."""
    mesh = make_mesh(dp=4, devices=jax.devices()[:4])
    loop = _pop_loop(mesh)
    st, buf, es, keys, ps = loop.init(jax.random.key(1), buffer_capacity=2_000)
    for leaf in (
        jax.tree_util.tree_leaves(st.actor_params)[0],
        buf.data.states,
        jax.tree_util.tree_leaves(es)[0],
        ps.return_ema,
    ):
        assert len(leaf.sharding.device_set) == 4, leaf.sharding
        assert not leaf.sharding.is_fully_replicated
    st, buf, es, keys, m = loop.epoch(
        st, buf, es, keys, steps=20, update_every=10, warmup=True
    )
    st, buf, es, keys, m = loop.epoch(st, buf, es, keys, steps=20, update_every=10)
    losses = np.asarray(m["loss_q"])
    assert losses.shape == (8,) and np.all(np.isfinite(losses))
    assert len(set(np.round(losses, 6))) > 1  # distinct curves
    out_leaf = jax.tree_util.tree_leaves(st.actor_params)[0]
    assert len(out_leaf.sharding.device_set) == 4
    assert not out_leaf.sharding.is_fully_replicated


def test_population_sharded_matches_unsharded_streams():
    """Sharding the member axis is a layout decision, not an
    algorithmic one: the collect/replay/loss streams match the
    unsharded population bitwise (each member's program is untouched;
    only its placement moved)."""
    def run(mesh):
        loop = _pop_loop(mesh)
        st, buf, es, keys, ps = loop.init(
            jax.random.key(1), buffer_capacity=2_000
        )
        st, buf, es, keys, _ = loop.epoch(
            st, buf, es, keys, steps=20, update_every=10, warmup=True
        )
        st, buf, es, keys, m = loop.epoch(
            st, buf, es, keys, steps=20, update_every=10
        )
        return st, m

    _, m_sharded = run(make_mesh(dp=4, devices=jax.devices()[:4]))
    _, m_plain = run(None)
    np.testing.assert_array_equal(
        np.asarray(m_sharded["loss_q"]), np.asarray(m_plain["loss_q"])
    )
    np.testing.assert_array_equal(
        np.asarray(m_sharded["reward"]), np.asarray(m_plain["reward"])
    )


def test_population_pbt_gather_crosses_devices():
    """The exploit step's member gather is a real cross-device
    collective now: force a ranking where the winner lives on another
    device than the loser and check the loser's params become the
    winner's (and keep the member sharding)."""
    from torch_actor_critic_tpu.sac.ondevice import PBTState

    mesh = make_mesh(dp=4, devices=jax.devices()[:4])
    loop = _pop_loop(mesh)
    st, buf, es, keys, ps = loop.init(jax.random.key(1), buffer_capacity=2_000)
    # Member 0 (device 0) is the worst, member 7 (device 3) the best;
    # all ranked -> exploit fires.
    ps = PBTState(
        return_ema=jnp.arange(8, dtype=jnp.float32),
        ema_count=jnp.ones(8, jnp.int32),
        rng=ps.rng,
    )
    new_st, new_ps, ev = loop.pbt_step(st, ps)
    src = np.asarray(ev["src"])
    exploited = np.flatnonzero(np.asarray(ev["exploited"]))
    assert exploited.size > 0 and set(exploited) <= {0, 1}
    for m in exploited:
        assert src[m] >= 6  # copied from the top quantile
        got = jax.tree_util.tree_leaves(
            loop.extract_member(new_st, int(m)).actor_params
        )
        want = jax.tree_util.tree_leaves(
            loop.extract_member(st, int(src[m])).actor_params
        )
        for a, b in zip(got, want):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    leaf = jax.tree_util.tree_leaves(new_st.actor_params)[0]
    assert len(leaf.sharding.device_set) == 4


def test_population_sharded_checkpoint_resume_is_bitwise(tmp_path):
    """PR 2/6 lossless-resume contract under the member sharding: save
    a sharded population mid-run, restore onto freshly-initialized
    sharded trees, continue — params and metrics match the
    uninterrupted run bitwise, and the restored arrays come back
    member-sharded."""
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer

    mesh = make_mesh(dp=4, devices=jax.devices()[:4])

    def fresh():
        loop = _pop_loop(mesh, pbt=False)
        return loop, *loop.init(jax.random.key(3), buffer_capacity=2_000)

    # Straight-through: 2 epochs, checkpointing after the first (the
    # epoch dispatch donates state+rings, so the save must happen
    # before the continuation consumes them).
    loop, st, buf, es, keys, ps = fresh()
    st, buf, es, keys, _ = loop.epoch(
        st, buf, es, keys, steps=20, update_every=10, warmup=True
    )
    st, buf, es, keys, m1 = loop.epoch(st, buf, es, keys, steps=20, update_every=10)
    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(
        0, st, buf,
        arrays={"env_states": es, "act_keys": keys},
        wait=True,
    )
    st, buf, es, keys, m2 = loop.epoch(st, buf, es, keys, steps=20, update_every=10)
    loop2, st2, buf2, es2, keys2, _ = fresh()
    st2, buf2, meta, arrays = ckpt.restore(
        st2, buf2,
        abstract_arrays={"env_states": es2, "act_keys": keys2},
    )
    ckpt.close()
    es2, keys2 = arrays["env_states"], arrays["act_keys"]
    leaf = jax.tree_util.tree_leaves(st2.actor_params)[0]
    assert len(leaf.sharding.device_set) == 4  # restored SHARDED
    assert not leaf.sharding.is_fully_replicated
    st2, buf2, es2, keys2, m2_resumed = loop2.epoch(
        st2, buf2, es2, keys2, steps=20, update_every=10
    )
    np.testing.assert_array_equal(
        np.asarray(m2_resumed["loss_q"]), np.asarray(m2["loss_q"])
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(st2.actor_params),
        jax.tree_util.tree_leaves(st.actor_params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_population_rejects_bad_meshes():
    """Indivisible populations and non-dp axes fail loudly at
    construction (the driver falls back to unsharded with a warning;
    the loop itself never silently mislays members)."""
    with pytest.raises(ValueError, match="divide evenly"):
        _pop_loop(make_mesh(dp=3, devices=jax.devices()[:3]), n_members=8)
    with pytest.raises(ValueError, match="dp mesh axis only"):
        _pop_loop(make_mesh(dp=2, fsdp=2, devices=jax.devices()[:4]))


def test_train_population_on_device_shards_when_divisible(tmp_path, caplog):
    """The driver wires the mesh through: a dp=4 mesh with population 8
    shards members (log line), an indivisible population falls back
    with a warning instead of failing."""
    import logging

    from torch_actor_critic_tpu.sac.ondevice import train_population_on_device

    cfg = SACConfig(
        hidden_sizes=(16, 16), batch_size=8, population=8,
        on_device_envs=2, steps_per_epoch=20, update_every=10,
        start_steps=10, epochs=1, buffer_size=2_000, pbt_every=0,
    )
    mesh = make_mesh(dp=4, devices=jax.devices()[:4])
    with caplog.at_level(logging.INFO, logger="torch_actor_critic_tpu.sac.ondevice"):
        metrics = train_population_on_device(
            "Pendulum-v1", cfg, mesh=mesh, seed=0
        )
    assert any(
        "sharding population=8 over dp=4" in r.getMessage()
        for r in caplog.records
    )
    assert all(np.isfinite(metrics[f"loss_q_m{i}"]) for i in range(8))

    cfg7 = cfg.replace(population=7)
    with caplog.at_level(logging.WARNING, logger="torch_actor_critic_tpu.sac.ondevice"):
        metrics7 = train_population_on_device(
            "Pendulum-v1", cfg7, mesh=mesh, seed=0
        )
    assert all(np.isfinite(metrics7[f"loss_q_m{i}"]) for i in range(7))


# --------------------------------------------- per-device cost division


def test_cost_registry_divides_by_mesh_size():
    """Satellite regression: registering the SAME dp=4 burst with and
    without ``devices=4`` must differ by exactly 4x on every cost
    column — roofline/MFU reads per-device FLOPs under dp>1."""
    from torch_actor_critic_tpu.telemetry.costmodel import CostRegistry

    sac = make_sac()
    dp = DataParallelSAC(sac, make_mesh(dp=4, devices=jax.devices()[:4]))
    state, buf, warm, chunk = _dp_inputs(dp)
    state, buf, _ = dp.update_burst(state, buf, warm, 2)
    fn = dp.burst_jit(2)
    assert fn is not None
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        (state, buf, chunk),
    )
    reg = CostRegistry()
    whole = reg.register_jit("whole", fn, *abstract)
    per_dev = reg.register_jit("per_dev", fn, *abstract, devices=4)
    assert whole is not None and per_dev is not None
    assert per_dev["devices"] == 4
    for k in ("flops", "bytes_accessed"):
        assert whole[k] > 0
        np.testing.assert_allclose(per_dev[k], whole[k] / 4, rtol=1e-9)
