"""Learning-health diagnostics tests (ISSUE 4 / docs/OBSERVABILITY.md).

Pins the tentpole contracts: ``off`` is a true no-op (exact historical
metric keys, diagnostics never perturb the training computation);
``light``/``full`` reductions match a NumPy reference exactly on a tiny
MLP; the suffix reduction convention holds through scan, mesh
collectives and host aggregation; dp skew catches replica state; the
drift monitor fires on scripted anomalies; and the recompilation
watchdog counts, attributes and flags compiles.
"""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torch_actor_critic_tpu.buffer import init_replay_buffer, push
from torch_actor_critic_tpu.core.types import Batch
from torch_actor_critic_tpu.diagnostics import (
    TD_HIST_GROWTH,
    TD_HIST_LO,
    DriftDetector,
    EarlyWarningMonitor,
    bucket_counts,
    get_watchdog,
    global_norm,
    make_td_histogram,
    norm_ratio,
    reduce_burst_metrics,
    reduce_metric_rows,
    reduction_for,
    replica_skew,
)
from torch_actor_critic_tpu.diagnostics.ingraph import TD_HIST_BUCKETS
from torch_actor_critic_tpu.models import Actor, DoubleCritic
from torch_actor_critic_tpu.sac import SAC, losses
from torch_actor_critic_tpu.utils.config import SACConfig

OBS_DIM, ACT_DIM = 4, 2

# The exact metric key set of a pre-diagnostics SAC update — the
# ``off``-tier parity target.
BASE_SAC_KEYS = {
    "loss_q", "loss_pi", "alpha", "q_mean", "backup_mean",
    "logp_pi", "entropy",
}


def make_sac(**overrides):
    cfg = SACConfig(hidden_sizes=(32, 32), batch_size=8, **overrides)
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=cfg.hidden_sizes, act_limit=1.0)
    critic = DoubleCritic(hidden_sizes=cfg.hidden_sizes, num_qs=cfg.num_qs)
    return SAC(cfg, actor, critic, ACT_DIM)


def make_batch(key, n=8):
    ks = jax.random.split(key, 5)
    return Batch(
        states=jax.random.normal(ks[0], (n, OBS_DIM)),
        actions=jnp.tanh(jax.random.normal(ks[1], (n, ACT_DIM))),
        rewards=jax.random.normal(ks[2], (n,)),
        next_states=jax.random.normal(ks[3], (n, OBS_DIM)),
        done=(jax.random.uniform(ks[4], (n,)) < 0.2).astype(jnp.float32),
    )


# ------------------------------------------------------------- off parity


def test_config_rejects_unknown_tier():
    with pytest.raises(ValueError, match="diagnostics"):
        SACConfig(diagnostics="verbose")


def test_off_tier_keys_and_bitwise_parity_with_full():
    """`off` emits exactly the historical key set, and the diagnostics
    computation is a pure observer: the off- and full-tier updates
    produce bitwise-identical training state and common metrics from
    the same inputs."""
    sac_off = make_sac(diagnostics="off")
    sac_full = make_sac(diagnostics="full")
    state = sac_off.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    batch = make_batch(jax.random.key(1))
    s_off, m_off = jax.jit(sac_off.update)(state, batch)
    s_full, m_full = jax.jit(sac_full.update)(state, batch)
    assert set(m_off) == BASE_SAC_KEYS
    assert BASE_SAC_KEYS < set(m_full)
    for k in BASE_SAC_KEYS:
        np.testing.assert_array_equal(np.asarray(m_off[k]), np.asarray(m_full[k]))
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, s_off.actor_params, s_full.actor_params
    )
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, s_off.critic_params, s_full.critic_params
    )


def test_off_tier_burst_keys_unchanged():
    sac = make_sac(diagnostics="off")
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    buf = init_replay_buffer(
        64, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM
    )
    buf = push(buf, make_batch(jax.random.key(5), n=32))
    _, _, m = jax.jit(sac.update_burst, static_argnums=(3,))(
        state, buf, make_batch(jax.random.key(6), n=10), 3
    )
    assert set(m) == BASE_SAC_KEYS
    assert all(v.shape == () for v in m.values())


# ------------------------------------------------- numpy-reference exactness


def _grads_and_key(sac, state, batch):
    """Replicate the update's internal critic grad computation (the
    frame_augment='none' parity 3-way rng split)."""
    _, key_q, _ = jax.random.split(state.rng, 3)
    grad_fn = jax.grad(losses.critic_loss, has_aux=True)
    grads, _ = grad_fn(
        state.critic_params,
        actor_apply=sac._actor_apply,
        critic_apply=sac._critic_apply,
        actor_params=state.actor_params,
        target_critic_params=state.target_critic_params,
        batch=batch,
        key=key_q,
        alpha=jnp.float32(sac.config.alpha),
        gamma=sac.config.gamma,
        reward_scale=sac.config.reward_scale,
    )
    return grads, key_q


def test_grad_norm_and_update_ratio_match_numpy():
    sac = make_sac(diagnostics="light")
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    batch = make_batch(jax.random.key(1))
    _, m = sac.update(state, batch)

    q_grads, _ = _grads_and_key(sac, state, batch)
    np_norm = math.sqrt(sum(
        float(np.sum(np.square(np.asarray(x, dtype=np.float32))))
        for x in jax.tree_util.tree_leaves(q_grads)
    ))
    assert float(m["diag/grad_norm_q"]) == pytest.approx(np_norm, rel=1e-5)

    # Update-to-param ratio against a manual optax step.
    q_updates, _ = sac.q_tx.update(
        q_grads, state.q_opt_state, state.critic_params
    )
    expected = float(global_norm(q_updates)) / (
        float(global_norm(state.critic_params)) + 1e-12
    )
    assert float(m["diag/update_ratio_q"]) == pytest.approx(expected, rel=1e-5)


def test_q_stats_match_numpy():
    sac = make_sac(diagnostics="full")
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    batch = make_batch(jax.random.key(1))
    _, m = sac.update(state, batch)

    _, key_q = _grads_and_key(sac, state, batch)
    _, aux = losses.critic_loss(
        state.critic_params,
        actor_apply=sac._actor_apply,
        critic_apply=sac._critic_apply,
        actor_params=state.actor_params,
        target_critic_params=state.target_critic_params,
        batch=batch,
        key=key_q,
        alpha=jnp.float32(sac.config.alpha),
        gamma=sac.config.gamma,
        reward_scale=sac.config.reward_scale,
        diagnostics=True,
    )
    q = np.asarray(aux["diag_q"])            # (num_qs, B)
    backup = np.asarray(aux["diag_backup"])  # (B,)
    assert float(m["diag/q_min"]) == pytest.approx(q.min(), rel=1e-6)
    assert float(m["diag/q_max"]) == pytest.approx(q.max(), rel=1e-6)
    assert float(m["diag/q_spread"]) == pytest.approx(
        (q.max(axis=0) - q.min(axis=0)).mean(), rel=1e-5
    )
    assert float(m["diag/q_bias"]) == pytest.approx(
        q.mean() - backup.mean(), rel=1e-4, abs=1e-6
    )
    # TD-error histogram: exact float32 mirror of the device bucketing.
    abs_td = np.abs(q - backup[None, :]).astype(np.float32).ravel()
    log_lo = np.float32(math.log(TD_HIST_LO))
    log_g = np.float32(math.log(TD_HIST_GROWTH))
    idx = np.floor(
        (np.log(np.maximum(abs_td, np.float32(TD_HIST_LO * 0.5)))
         - log_lo) / log_g
    ).astype(np.int32) + 1
    idx = np.where(abs_td < TD_HIST_LO, 0, np.clip(idx, 1, TD_HIST_BUCKETS + 1))
    expected_counts = np.bincount(idx, minlength=TD_HIST_BUCKETS + 2)
    np.testing.assert_array_equal(np.asarray(m["diag/td_hist"]), expected_counts)
    assert float(m["diag/td_abs_max"]) == pytest.approx(abs_td.max(), rel=1e-6)
    assert float(m["diag/td_abs_sum"]) == pytest.approx(abs_td.sum(), rel=1e-4)


def test_td_histogram_host_merge_roundtrip():
    """Device counts merge into the telemetry histogram schema with
    exact count/total/min/max and bounded-error percentiles."""
    rng = np.random.default_rng(7)
    vals = rng.lognormal(0.0, 1.5, 20_000).astype(np.float32)
    counts = np.asarray(bucket_counts(jnp.asarray(vals)))
    hist = make_td_histogram()
    assert len(counts) == hist.n_buckets + 2
    hist.merge_counts(
        counts, total=float(vals.sum()),
        vmin=float(vals.min()), vmax=float(vals.max()),
    )
    assert hist.count == len(vals)
    assert hist.mean == pytest.approx(vals.mean(), rel=1e-4)
    assert hist.max == pytest.approx(vals.max(), rel=1e-6)
    for q in (50, 95, 99):
        assert hist.percentile(q) == pytest.approx(
            np.percentile(vals, q), rel=0.25
        ), q
    snap = hist.snapshot(prefix="td_abs_", unit="")
    assert snap["td_abs_count"] == len(vals)
    assert "td_abs_p99" in snap and "td_abs_p99_ms" not in snap
    with pytest.raises(ValueError, match="bucket spec"):
        hist.merge_counts([1, 2, 3])


def test_bucket_counts_edge_cases():
    vals = jnp.asarray(
        [0.0, TD_HIST_LO / 2, 1.0, -1.0, 1e9, jnp.nan, jnp.inf]
    )
    counts = np.asarray(bucket_counts(vals))
    assert counts.sum() == 5          # nan/inf dropped
    assert counts[0] == 2             # 0.0 and lo/2 underflow
    assert counts[-1] == 1            # 1e9 overflows


# ----------------------------------------------------- reduction convention


def test_reduction_suffix_rules():
    assert reduction_for("loss_q") == "mean"
    assert reduction_for("q_mean") == "mean"  # historical key: mean
    assert reduction_for("loss_q_max") == "max"
    assert reduction_for("diag/q_min") == "min"
    assert reduction_for("diag/td_hist") == "sum"
    assert reduction_for("diag/td_abs_sum") == "sum"

    metrics = {
        "loss_q": jnp.asarray([1.0, 3.0, 2.0]),
        "loss_q_max": jnp.asarray([1.0, 3.0, 2.0]),
        "diag/q_min": jnp.asarray([1.0, -3.0, 2.0]),
        "diag/td_hist": jnp.ones((3, 4), jnp.int32),
    }
    out = reduce_burst_metrics(metrics)
    assert float(out["loss_q"]) == 2.0
    assert float(out["loss_q_max"]) == 3.0
    assert float(out["diag/q_min"]) == -3.0
    np.testing.assert_array_equal(np.asarray(out["diag/td_hist"]), [3, 3, 3, 3])

    rows = [
        {"a_max": np.asarray(1.0), "h_hist": np.ones((2, 4))},
        {"a_max": np.asarray(5.0), "h_hist": np.ones((2, 4))},
    ]
    host = reduce_metric_rows(rows)
    assert host["a_max"] == 5.0
    # Member axis folded, bucket axis kept.
    np.testing.assert_array_equal(host["h_hist"], [4, 4, 4, 4])


def test_replica_skew_under_shard_map():
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.parallel.context import manual_shard_map as shard_map
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(dp=4)

    def body(_):
        v = jax.lax.axis_index("dp").astype(jnp.float32)
        skew = replica_skew({"diag/param_norm": v}, ("diag/param_norm",), "dp")
        return skew["diag/param_norm_skew"]

    out = shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=P(),
        check_vma=False,
    )(jnp.zeros(4))
    assert float(out) == 3.0  # pmax(0..3) - pmin(0..3)


def test_dp_burst_skew_metrics():
    """dp=2 burst: healthy replicas show grad-norm skew > 0 (distinct
    replay shards) and param-norm skew == 0.0 exactly (pmean'd grads
    keep replicas bit-identical) — the desync canary reads clean."""
    from torch_actor_critic_tpu.parallel import (
        DataParallelSAC,
        init_sharded_buffer,
        make_mesh,
        shard_chunk,
    )

    sac = make_sac(diagnostics="light")
    dp = DataParallelSAC(sac, make_mesh(dp=2))
    state = dp.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    buf = init_sharded_buffer(
        64, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM, dp.mesh
    )
    ks = jax.random.split(jax.random.key(1), 5)
    chunk = Batch(
        states=jax.random.normal(ks[0], (2, 16, OBS_DIM)),
        actions=jnp.tanh(jax.random.normal(ks[1], (2, 16, ACT_DIM))),
        rewards=jax.random.normal(ks[2], (2, 16)),
        next_states=jax.random.normal(ks[3], (2, 16, OBS_DIM)),
        done=jnp.zeros((2, 16)),
    )
    _, _, m = dp.update_burst(
        state, buf, shard_chunk(chunk, dp.mesh), 4
    )
    assert float(m["diag/param_norm_skew"]) == 0.0
    assert float(m["diag/grad_norm_q_skew"]) > 0.0
    assert float(m["diag/grad_norm_pi_skew"]) > 0.0
    assert float(m["loss_q_max"]) >= float(m["loss_q"]) - 1e-6


# --------------------------------------------------------- early warnings


def test_drift_detector_grad_spike_and_warmup():
    d = DriftDetector("grad_spike", "diag/grad_norm_q", "high", k=6, warmup=3)
    # Warmup: even a large excursion inside the first `warmup` samples
    # must not fire.
    assert d.update(1.0) is None
    assert d.update(50.0) is None
    for v in (1.0, 1.05, 0.95, 1.0):
        d.update(v)
    w = d.update(100.0)
    assert w is not None and w["kind"] == "grad_spike"
    # The clipped EMA refuses to swallow the spike: the next normal
    # value does not fire low/new baselines.
    assert d.update(1.0) is None


def test_drift_detector_directions():
    low = DriftDetector("entropy_collapse", "entropy", "low", k=6, warmup=2)
    for v in (1.0, 1.0, 1.01, 0.99, 1.0):
        assert low.update(v) is None
    assert low.update(-2.0) is not None   # collapse fires
    assert low.update(1.0) is None        # recovery (upward) never fires

    shift = DriftDetector("q_bias_drift", "diag/q_bias", "shift", k=6, warmup=2)
    for v in (-0.5, -0.5, -0.52, -0.48, -0.5):
        assert shift.update(v) is None
    assert shift.update(-8.0) is not None  # drift in either direction
    shift2 = DriftDetector("q_bias_drift", "diag/q_bias", "shift", k=6, warmup=2)
    for v in (-0.5, -0.5, -0.52, -0.48, -0.5):
        assert shift2.update(v) is None
    assert shift2.update(7.0) is not None


def test_monitor_feeds_sentinel():
    from torch_actor_critic_tpu.resilience.sentinel import DivergenceSentinel

    mon = EarlyWarningMonitor(k=6, warmup=2)
    sentinel = DivergenceSentinel()
    for _ in range(5):
        ws = mon.update({
            "diag/grad_norm_q": 1.0, "diag/grad_norm_pi": 1.0,
            "entropy": 0.5, "diag/q_bias": -0.1,
        })
        assert ws == []
    ws = mon.update({
        "diag/grad_norm_q": 500.0, "diag/grad_norm_pi": 1.0,
        "entropy": 0.5, "diag/q_bias": -0.1,
    })
    assert [w["kind"] for w in ws] == ["grad_spike"]
    for w in ws:
        sentinel.note_warning(w["kind"])
    assert sentinel.warnings_total == 1
    assert sentinel.warnings_by_kind == {"grad_spike": 1}
    assert sentinel.consecutive == 0  # no rollback budget consumed
    # Non-finite values are the sentinel's business, not the monitor's.
    assert mon.update({"diag/grad_norm_q": float("nan")}) == []


# ------------------------------------------------------------- watchdog


def test_watchdog_counts_attributes_and_flags():
    wd = get_watchdog().install()
    wd.reset()
    try:
        f = jax.jit(lambda x: x * 2.0 + 1.0)
        with wd.source("train/update_burst"):
            f(jnp.ones(7))
        snap = wd.snapshot()
        assert snap["compiles_total"] >= 1
        assert snap["by_source"].get("train/update_burst", 0) >= 1
        assert snap["post_steady_compiles"] == 0

        wd.mark_steady("train/")
        with wd.source("train/update_burst"):
            f(jnp.ones(13))  # new shape: an induced steady-state recompile
        snap = wd.snapshot()
        assert snap["post_steady_compiles"] >= 1
        assert snap["anomalies"][0]["source"] == "train/update_burst"

        # expected() (warmup inside a steady regime): counted, not flagged.
        before = wd.snapshot()["post_steady_compiles"]
        total_before = wd.snapshot()["compiles_total"]
        with wd.expected(), wd.source("train/update_burst"):
            f(jnp.ones(17))
        snap = wd.snapshot()
        assert snap["post_steady_compiles"] == before
        assert snap["compiles_total"] > total_before

        # Unattributed compiles never flag (only steady prefixes do).
        jax.jit(lambda x: x - 3.0)(jnp.ones(3))
        assert wd.snapshot()["post_steady_compiles"] == before
    finally:
        wd.reset()


def test_engine_compile_counts_warmup_vs_live():
    from torch_actor_critic_tpu.serve.engine import PolicyEngine

    actor = Actor(act_dim=2, hidden_sizes=(8, 8))
    params = actor.init(
        jax.random.key(0), jnp.zeros((3,)), jax.random.key(1)
    )
    spec = jax.ShapeDtypeStruct((3,), jnp.float32)
    eng = PolicyEngine(actor, spec, max_batch=4)
    eng.warmup(params)
    s = eng.compile_stats()
    assert s["compiles_total"] == len(eng.buckets) * 2
    assert s["live_compiles"] == 0
    assert all(
        b["warmup"] == 2 and b["live"] == 0 for b in s["buckets"].values()
    )
    # Repeat traffic adds no compiles.
    eng.act(params, np.zeros((3, 3), np.float32), deterministic=True)
    assert eng.compile_stats() == s

    # A bucket skipped at warmup shows up as a LIVE compile.
    eng2 = PolicyEngine(actor, spec, max_batch=4)
    eng2.warmup(params, buckets=[2])
    eng2.act(params, np.zeros((4, 3), np.float32), deterministic=True)
    s2 = eng2.compile_stats()
    assert s2["live_compiles"] == 1
    assert s2["buckets"]["4"] == {"warmup": 0, "live": 1, "bundle": 0}


def test_server_metrics_exposes_compiles_and_xla():
    from urllib import request as urlreq

    from torch_actor_critic_tpu.serve import ModelRegistry, PolicyServer

    actor = Actor(act_dim=2, hidden_sizes=(8, 8))
    params = actor.init(
        jax.random.key(0), jnp.zeros((3,)), jax.random.key(1)
    )
    reg = ModelRegistry()
    reg.register(
        "default", actor, jax.ShapeDtypeStruct((3,), jnp.float32),
        params=params, max_batch=2,
    )
    with PolicyServer(reg, port=0, max_batch=2) as srv:
        srv.start()
        snap = json.loads(
            urlreq.urlopen(srv.address + "/metrics", timeout=30).read()
        )
    assert snap["compiles_total"] == 2  # one bucket x (det, sampled)
    assert snap["live_compiles"] == 0
    assert snap["compiles"]["default"]["buckets"]["2"]["warmup"] == 2
    assert snap["xla"]["compiles_total"] >= 2
    assert isinstance(snap["xla"]["by_source"], dict)


# ------------------------------------------------------ trainer integration


def test_trainer_light_tier_metrics(tmp_path):
    """Light tier through the real Trainer (no telemetry): diagnostic
    scalars, early_warnings and xla_compiles land in metrics.jsonl; no
    TD-histogram keys (full-only)."""
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.tracking import Tracker

    tracker = Tracker(experiment="t", root=tmp_path)
    cfg = SACConfig(
        hidden_sizes=(16, 16), batch_size=16, epochs=2, steps_per_epoch=30,
        start_steps=10, update_after=10, update_every=10, buffer_size=500,
        max_ep_len=100, diagnostics="light",
    )
    tr = Trainer(
        "Pendulum-v1", cfg, mesh=make_mesh(dp=1), tracker=tracker, seed=3
    )
    try:
        metrics = tr.train()
    finally:
        tr.close()
    for key in (
        "diag/grad_norm_q", "diag/update_ratio_pi", "diag/q_bias",
        "diag/act_sat", "diag/param_norm", "loss_q_max",
        "early_warnings", "xla_compiles",
    ):
        assert key in metrics, key
        assert np.isfinite(metrics[key]), key
    assert "diag/td_abs_sum" not in metrics  # full-tier only
    assert tr.td_hist.count == 0
    rows = tracker.metrics()
    assert all("diag/grad_norm_q" in r for r in rows)
