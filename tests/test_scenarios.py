"""scenarios/: multi-agent, procedural, and multi-task workloads.

Covers the PR's acceptance contract:

- the multi-agent scenario trains under the fused on-device loop with
  per-agent metrics;
- the procedural family provably varies its level per episode off the
  env PRNG stream (two episodes, same policy, different level params);
- multi-task training stripes replay per task and serves each trained
  task as its own slot on the existing multi-slot registry;
- existing single-agent scenario paths stay bitwise-unchanged (loop
  routing, metric-key set, and an output-bitwise pin of the scenario
  loop against the base loop on a classic env);
- `get_on_device_env` unknown-name errors list the registered
  scenario names;
- `history_env` composes over the scenario classes (level params /
  agent-task structure preserved).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_actor_critic_tpu.buffer.striped import (
    StripedBufferState,
    init_striped_replay_buffer,
    push_striped,
    sample_striped,
)
from torch_actor_critic_tpu.core.types import Batch
from torch_actor_critic_tpu.diagnostics.ingraph import split_scenario_metrics
from torch_actor_critic_tpu.envs.ondevice import (
    ON_DEVICE_ENVS,
    get_on_device_env,
    history_env,
    known_on_device_envs,
)
from torch_actor_critic_tpu.sac.ondevice import (
    OnDeviceLoop,
    PopulationOnDeviceLoop,
    _wrap_and_build,
    loop_class_for,
)
from torch_actor_critic_tpu.scenarios import (
    HurdleRunnerJax,
    PendulumMultiTaskJax,
    get_scenario,
    multi_agent_pendulum,
    register_scenario,
    scenario_names,
)
from torch_actor_critic_tpu.scenarios.loop import ScenarioOnDeviceLoop
from torch_actor_critic_tpu.scenarios.serving import (
    TaskSlotPolicy,
    register_scenario_slots,
    scenario_slot_names,
)
from torch_actor_critic_tpu.utils.config import SACConfig


def small_config(**kw):
    base = dict(hidden_sizes=(16, 16), batch_size=15, buffer_size=3000)
    base.update(kw)
    return SACConfig(**base)


def short_env(env_cls, steps=10):
    """Subclass an on-device env with a short episode so epoch tests
    finish episodes; classmethods read limits through ``cls``."""
    cls = type(f"Short{env_cls.__name__}", (env_cls,), {})
    cls.max_episode_steps = steps
    return cls


def run_loop(loop_cls, sac, env_cls, n_envs=4, seed=0, capacity=3000):
    """One fused train epoch (no separate warmup program — the burst
    pushes its chunk before sampling, so the ring is never empty).
    Keeps the per-test compile count at one epoch program."""
    loop = loop_cls(sac, env_cls, n_envs=n_envs)
    ts, buf, es, key = loop.init(jax.random.key(seed), buffer_capacity=capacity)
    ts, buf, es, key, m = loop.epoch(ts, buf, es, key, steps=20, update_every=10)
    return loop, ts, buf, m


def leaf_bytes(tree):
    out = []
    for x in jax.tree_util.tree_leaves(tree):
        if jax.dtypes.issubdtype(
            getattr(x, "dtype", jnp.float32), jax.dtypes.prng_key
        ):
            x = jax.random.key_data(x)
        out.append(np.asarray(x).tobytes())
    return out


# ---------------------------------------------------------------- registry


class TestRegistry:
    def test_builtin_scenarios_registered(self):
        assert {
            "multi-pendulum-2", "multi-pendulum-4", "hurdle-runner",
            "pendulum-multitask",
        } <= set(scenario_names())

    def test_get_scenario_unknown_lists_names(self):
        with pytest.raises(ValueError) as e:
            get_scenario("definitely-not-a-scenario")
        msg = str(e.value)
        for name in scenario_names():
            assert name in msg
        assert "Pendulum-v1" in msg  # the full on-device list rides along

    def test_get_on_device_env_resolves_scenarios(self):
        assert get_on_device_env("hurdle-runner") is HurdleRunnerJax
        assert get_on_device_env("pendulum-multitask") is PendulumMultiTaskJax
        assert get_on_device_env("no-such-env") is None

    def test_known_envs_superset(self):
        known = known_on_device_envs()
        assert set(ON_DEVICE_ENVS) <= set(known)
        assert set(scenario_names()) <= set(known)

    def test_register_collision_raises(self):
        with pytest.raises(ValueError, match="already registered"):
            register_scenario("hurdle-runner", HurdleRunnerJax)
        with pytest.raises(ValueError, match="already registered"):
            register_scenario("Pendulum-v1", HurdleRunnerJax)

    def test_train_driver_unknown_env_lists_scenarios(self):
        from torch_actor_critic_tpu.sac.ondevice import train_on_device

        with pytest.raises(ValueError) as e:
            train_on_device("no-such-env", small_config(on_device=True))
        assert "hurdle-runner" in str(e.value)
        assert "pendulum-multitask" in str(e.value)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="ma_critic"):
            SACConfig(ma_critic="nope")
        with pytest.raises(ValueError, match="task_embed_dim"):
            SACConfig(task_embed_dim=-1)


# ------------------------------------------------- single-agent pin


class TestSingleAgentPin:
    def test_loop_routing(self):
        for cls in set(ON_DEVICE_ENVS.values()):
            assert loop_class_for(cls) is OnDeviceLoop
        # Procedural env has no agent/task structure: base loop.
        assert loop_class_for(HurdleRunnerJax) is OnDeviceLoop
        assert loop_class_for(PendulumMultiTaskJax) is ScenarioOnDeviceLoop
        assert loop_class_for(multi_agent_pendulum(2)) is ScenarioOnDeviceLoop

    def test_scenario_loop_bitwise_on_classic_env(self):
        """The scenario machinery must be a no-op for classic envs:
        same metric keys, bitwise-equal state and metrics."""
        cfg = small_config(batch_size=16, buffer_size=2000)
        env_cls, sac = _wrap_and_build(ON_DEVICE_ENVS["Pendulum-v1"], cfg)
        _, ts_a, _, m_a = run_loop(OnDeviceLoop, sac, env_cls, capacity=2000)
        _, ts_b, _, m_b = run_loop(
            ScenarioOnDeviceLoop, sac, env_cls, capacity=2000
        )
        assert sorted(m_a) == sorted(m_b) == [
            "episodes", "loss_pi", "loss_q", "reward",
        ]
        for k in m_a:
            assert np.array_equal(
                np.asarray(m_a[k]), np.asarray(m_b[k]), equal_nan=True
            ), k
        assert leaf_bytes(ts_a) == leaf_bytes(ts_b)

    def test_split_scenario_metrics_scalars_passthrough(self):
        m = {"loss_q": jnp.float32(1.5), "reward": np.float32(-3.0)}
        assert split_scenario_metrics(m) == {"loss_q": 1.5, "reward": -3.0}

    def test_split_scenario_metrics_axes(self):
        out = split_scenario_metrics({
            "reward_per_agent": np.array([1.0, 2.0]),
            "reward_per_task": np.array([3.0, 4.0, 5.0]),
            "other_vec": np.array([6.0, 7.0]),
        })
        assert out == {
            "reward_a0": 1.0, "reward_a1": 2.0,
            "reward_t0": 3.0, "reward_t1": 4.0, "reward_t2": 5.0,
            "other_vec_0": 6.0, "other_vec_1": 7.0,
        }


# ------------------------------------------------------------ multi-agent


class TestMultiAgent:
    def test_env_shapes_and_team_reward(self):
        env = multi_agent_pendulum(3)
        st = env.reset(jax.random.key(0))
        assert st.obs.shape == (21,)
        st2, out = env.step(st, jnp.zeros(3))
        assert st2.obs.shape == (21,)
        assert out.extras["return_per_agent"].shape == (3,)
        # Team reward is the per-agent mean: recompute from the pre-step
        # state (theta, theta_dot) and the zero action.
        theta, theta_dot, _ = st.inner
        angle = ((theta + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        expected = jnp.mean(-(angle**2 + 0.1 * theta_dot**2))
        np.testing.assert_allclose(
            float(out.reward), float(expected), rtol=1e-6
        )

    def test_actor_factorization(self):
        """Zeroing agent 0's head params must not move agent 1's
        action (decentralized per-agent heads, one joint sample)."""
        cfg = small_config()
        env_cls, sac = _wrap_and_build(multi_agent_pendulum(2), cfg)
        ts = sac.init_state(jax.random.key(0), jnp.zeros(env_cls.obs_dim))
        obs = jnp.ones((5, env_cls.obs_dim))
        key = jax.random.key(7)
        a_ref, _ = sac.actor_def.apply(ts.actor_params, obs, key)

        def zero_agent0(x):
            return x.at[0].set(0.0) if x.ndim >= 1 else x

        params0 = jax.tree_util.tree_map(zero_agent0, ts.actor_params)
        a_cut, _ = sac.actor_def.apply(params0, obs, key)
        assert not np.allclose(a_cut[:, 0], a_ref[:, 0])  # agent 0 moved
        np.testing.assert_array_equal(a_cut[:, 1], a_ref[:, 1])  # agent 1 pinned

    def test_trains_with_per_agent_metrics(self):
        cfg = small_config(batch_size=16)
        env_cls, sac = _wrap_and_build(short_env(multi_agent_pendulum(2)), cfg)
        loop_cls = loop_class_for(env_cls)
        assert loop_cls is ScenarioOnDeviceLoop
        _, _, _, m = run_loop(loop_cls, sac, env_cls)
        assert np.isfinite(float(m["loss_q"]))
        assert np.isfinite(float(m["loss_pi"]))
        assert m["reward_per_agent"].shape == (2,)
        assert np.all(np.isfinite(np.asarray(m["reward_per_agent"])))

    def test_per_agent_critic_is_vdn_sum(self):
        cfg = small_config(ma_critic="per_agent")
        env_cls, sac = _wrap_and_build(multi_agent_pendulum(2), cfg)
        from torch_actor_critic_tpu.models import MultiAgentDoubleCritic

        assert isinstance(sac.critic_def, MultiAgentDoubleCritic)
        ts = sac.init_state(jax.random.key(0), jnp.zeros(env_cls.obs_dim))
        obs = jnp.ones((4, env_cls.obs_dim))
        act = jnp.full((4, env_cls.act_dim), 0.3)
        q = sac.critic_def.apply(ts.critic_params, obs, act)
        assert q.shape == (cfg.num_qs, 4)
        assert np.all(np.isfinite(np.asarray(q)))

    def test_centralized_critic_is_plain_double_critic(self):
        cfg = small_config()  # ma_critic defaults to centralized
        _, sac = _wrap_and_build(multi_agent_pendulum(2), cfg)
        from torch_actor_critic_tpu.models import DoubleCritic

        assert type(sac.critic_def) is DoubleCritic


# ------------------------------------------------------------- procedural


class TestProcedural:
    def test_reset_deterministic_per_key(self):
        a = HurdleRunnerJax.level_params(HurdleRunnerJax.reset(jax.random.key(3)))
        b = HurdleRunnerJax.level_params(HurdleRunnerJax.reset(jax.random.key(3)))
        c = HurdleRunnerJax.level_params(HurdleRunnerJax.reset(jax.random.key(4)))
        np.testing.assert_array_equal(a["hurdle_x"], b["hurdle_x"])
        assert not np.allclose(a["hurdle_x"], c["hurdle_x"])

    def test_level_varies_per_episode_same_policy(self):
        """The acceptance pin: run two consecutive episodes under the
        SAME (zero) policy; the auto-reset draws a fresh level off the
        env PRNG stream, so every level parameter re-rolls."""
        st = HurdleRunnerJax.reset(jax.random.key(11))
        first = HurdleRunnerJax.level_params(st)
        step = jax.jit(HurdleRunnerJax.step)
        zero = jnp.zeros(HurdleRunnerJax.act_dim)
        ended = False
        for _ in range(HurdleRunnerJax.max_episode_steps):
            st, out = step(st, zero)
            ended = bool(out.ended)
        assert ended
        second = HurdleRunnerJax.level_params(st)
        assert not np.allclose(first["hurdle_x"], second["hurdle_x"])
        assert not np.allclose(first["hurdle_h"], second["hurdle_h"])
        assert float(first["target_speed"]) != float(second["target_speed"])

    def test_trains_under_base_loop(self):
        cfg = small_config(batch_size=16)
        env_cls, sac = _wrap_and_build(short_env(HurdleRunnerJax), cfg)
        assert loop_class_for(env_cls) is OnDeviceLoop
        _, _, _, m = run_loop(OnDeviceLoop, sac, env_cls)
        assert sorted(m) == ["episodes", "loss_pi", "loss_q", "reward"]
        assert np.isfinite(float(m["loss_q"]))
        assert np.isfinite(float(m["reward"]))

    def test_obs_reads_next_hurdles(self):
        st = HurdleRunnerJax.reset(jax.random.key(5))
        lp = HurdleRunnerJax.level_params(st)
        d0 = float(st.obs[5]) * 20.0  # nearest hurdle, de-normalized
        np.testing.assert_allclose(
            d0, float(np.min(np.asarray(lp["hurdle_x"]))), rtol=1e-5
        )


# -------------------------------------------------------------- multi-task


class TestMultiTask:
    def test_task_persists_across_auto_reset(self):
        env = short_env(PendulumMultiTaskJax, steps=5)
        st = jax.vmap(env.reset)(jax.random.split(jax.random.key(0), 8))
        tasks0 = np.asarray(st.inner[0])
        step = jax.jit(jax.vmap(env.step))
        for _ in range(12):  # crosses at least two auto-resets
            st, out = step(st, jnp.zeros((8, 1)))
        np.testing.assert_array_equal(np.asarray(st.inner[0]), tasks0)

    def test_striped_push_routes_by_task(self):
        n, t_dim = 12, 3
        obs_spec = jax.ShapeDtypeStruct((PendulumMultiTaskJax.obs_dim,), jnp.float32)
        buf = init_striped_replay_buffer(300, obs_spec, 1, t_dim)
        assert buf.capacity == 100
        tasks = np.array([0, 1, 2, 2, 1, 0, 0, 0, 2, 1, 1, 1])
        obs = np.zeros((n, 6), np.float32)
        obs[np.arange(n), 3 + tasks] = 1.0
        obs[:, 0] = np.arange(n)  # row tag
        chunk = Batch(
            states=jnp.asarray(obs),
            actions=jnp.zeros((n, 1)),
            rewards=jnp.arange(n, dtype=jnp.float32),
            next_states=jnp.asarray(obs),
            done=jnp.zeros(n),
        )
        buf = jax.jit(push_striped)(buf, chunk)
        np.testing.assert_array_equal(
            np.asarray(buf.size), np.bincount(tasks, minlength=t_dim)
        )
        # Every stored row sits in its task's stripe, in push order.
        for task in range(t_dim):
            rows = np.asarray(buf.data.rewards[task][: buf.size[task]])
            np.testing.assert_array_equal(rows, np.flatnonzero(tasks == task))

    def test_striped_sample_is_task_balanced(self):
        t_dim = 3
        obs_spec = jax.ShapeDtypeStruct((6,), jnp.float32)
        buf = init_striped_replay_buffer(300, obs_spec, 1, t_dim)
        # Wildly imbalanced pushes: 60 of task 0, 3 of task 1, 9 of 2.
        tasks = np.array([0] * 60 + [1] * 3 + [2] * 9)
        obs = np.zeros((len(tasks), 6), np.float32)
        obs[np.arange(len(tasks)), 3 + tasks] = 1.0
        chunk = Batch(
            states=jnp.asarray(obs),
            actions=jnp.zeros((len(tasks), 1)),
            rewards=jnp.zeros(len(tasks)),
            next_states=jnp.asarray(obs),
            done=jnp.zeros(len(tasks)),
        )
        buf = push_striped(buf, chunk)
        batch = jax.jit(
            lambda b, k: sample_striped(b, k, 15)
        )(buf, jax.random.key(0))
        sampled_tasks = np.argmax(np.asarray(batch.states[:, 3:]), axis=-1)
        np.testing.assert_array_equal(
            np.bincount(sampled_tasks, minlength=t_dim), [5, 5, 5]
        )

    def test_striped_wraparound_saturates(self):
        obs_spec = jax.ShapeDtypeStruct((6,), jnp.float32)
        buf = init_striped_replay_buffer(12, obs_spec, 1, 3)  # 4 per stripe
        obs = np.zeros((3, 6), np.float32)
        obs[np.arange(3), 3 + np.arange(3)] = 1.0
        chunk = Batch(
            states=jnp.asarray(obs), actions=jnp.zeros((3, 1)),
            rewards=jnp.zeros(3), next_states=jnp.asarray(obs),
            done=jnp.zeros(3),
        )
        for _ in range(7):
            buf = push_striped(buf, chunk)
        np.testing.assert_array_equal(np.asarray(buf.size), [4, 4, 4])
        np.testing.assert_array_equal(np.asarray(buf.ptr), [3, 3, 3])

    def test_trains_with_striped_replay_and_per_task_metrics(self):
        cfg = small_config()
        env_cls, sac = _wrap_and_build(short_env(PendulumMultiTaskJax), cfg)
        loop, _, buf, m = run_loop(
            loop_class_for(env_cls), sac, env_cls, n_envs=8
        )
        assert isinstance(loop, ScenarioOnDeviceLoop)
        assert isinstance(buf, StripedBufferState)
        assert m["reward_per_task"].shape == (3,)
        assert m["episodes_per_task"].shape == (3,)
        assert float(jnp.sum(m["episodes_per_task"])) == float(m["episodes"])
        host = split_scenario_metrics(jax.device_get(m))
        assert {"reward_t0", "reward_t1", "reward_t2"} <= set(host)

    def test_task_embedding_heads(self):
        cfg = small_config(task_embed_dim=4)
        env_cls, sac = _wrap_and_build(PendulumMultiTaskJax, cfg)
        from torch_actor_critic_tpu.models import (
            TaskConditionedActor,
            TaskConditionedDoubleCritic,
        )

        assert isinstance(sac.actor_def, TaskConditionedActor)
        assert isinstance(sac.critic_def, TaskConditionedDoubleCritic)
        ts = sac.init_state(jax.random.key(0), jnp.zeros(env_cls.obs_dim))
        obs = jnp.concatenate(
            [jnp.ones((4, 3)), jax.nn.one_hot(jnp.arange(4) % 3, 3)], axis=-1
        )
        act, logp = sac.actor_def.apply(ts.actor_params, obs, jax.random.key(1))
        assert act.shape == (4, 1) and np.all(np.isfinite(np.asarray(act)))
        assert np.all(np.isfinite(np.asarray(logp)))
        # The embedding conditions the policy: different tasks, same
        # base features, different deterministic actions.
        det, _ = sac.actor_def.apply(
            ts.actor_params, obs, deterministic=True, with_logprob=False
        )
        assert not np.allclose(det[0], det[1])
        q = sac.critic_def.apply(ts.critic_params, obs, act)
        assert q.shape == (cfg.num_qs, 4)
        assert np.all(np.isfinite(np.asarray(q)))

    @pytest.mark.slow
    def test_population_over_multitask(self):
        """Member-vmapped scenario epochs (striped rings + per-task
        extras under the population axis). Slow tier: the vmapped
        compile is the costliest in this file, and the composition is
        also gated by scenario_smoke's bitwise population resume."""
        cfg = small_config()
        env_cls, sac = _wrap_and_build(short_env(PendulumMultiTaskJax), cfg)
        pop = PopulationOnDeviceLoop(sac, env_cls, n_members=2, n_envs=4)
        assert isinstance(pop.inner, ScenarioOnDeviceLoop)
        st, buf, es, keys, _ = pop.init(jax.random.key(0), buffer_capacity=3000)
        st, buf, es, keys, m = pop.epoch(
            st, buf, es, keys, steps=20, update_every=10
        )
        assert m["loss_q"].shape == (2,)
        assert m["reward_per_task"].shape == (2, 3)
        assert np.all(np.isfinite(np.asarray(m["loss_q"])))


# ----------------------------------------------------------- history_env


class TestHistoryComposition:
    def test_history_over_procedural_preserves_level(self):
        wrapped = history_env(HurdleRunnerJax, 4)
        st = wrapped.reset(jax.random.key(0))
        assert st.obs.shape == (4, HurdleRunnerJax.obs_dim)
        level = HurdleRunnerJax.level_params(st.inner)
        st2, out = jax.jit(wrapped.step)(st, jnp.zeros(2))
        assert out.next_obs.shape == (4, HurdleRunnerJax.obs_dim)
        level2 = HurdleRunnerJax.level_params(st2.inner)
        # Mid-episode: the level rides the window unchanged.
        np.testing.assert_array_equal(
            np.asarray(level["hurdle_x"]), np.asarray(level2["hurdle_x"])
        )

    def test_history_forwards_scenario_attrs(self):
        wrapped = history_env(PendulumMultiTaskJax, 3)
        assert wrapped.n_tasks == 3
        assert wrapped.base_obs_dim == 3
        ma = history_env(multi_agent_pendulum(2), 3)
        assert ma.n_agents == 2
        assert ma.agent_obs_dim == 7

    def test_striped_task_recovery_from_windowed_obs(self):
        """The striped ring reads the task one-hot from the newest
        frame of a history window."""
        wrapped = history_env(PendulumMultiTaskJax, 3)
        obs_spec = jax.ShapeDtypeStruct(wrapped.obs_shape, jnp.float32)
        buf = init_striped_replay_buffer(30, obs_spec, 1, 3)
        obs = np.zeros((6, 3, 6), np.float32)
        tasks = np.array([2, 0, 1, 1, 0, 2])
        obs[np.arange(6), :, 3 + tasks] = 1.0
        chunk = Batch(
            states=jnp.asarray(obs), actions=jnp.zeros((6, 1)),
            rewards=jnp.zeros(6), next_states=jnp.asarray(obs),
            done=jnp.zeros(6),
        )
        buf = push_striped(buf, chunk)
        np.testing.assert_array_equal(np.asarray(buf.size), [2, 2, 2])

    def test_multi_agent_history_fails_at_construction(self):
        cfg = small_config(history_len=3)
        with pytest.raises(ValueError, match="flat"):
            _wrap_and_build(multi_agent_pendulum(2), cfg)


# --------------------------------------------------------------- serving


class TestServing:
    @pytest.fixture(scope="class")
    def multitask_setup(self):
        cfg = small_config()
        env_cls, sac = _wrap_and_build(PendulumMultiTaskJax, cfg)
        ts = sac.init_state(jax.random.key(0), jnp.zeros(env_cls.obs_dim))
        return env_cls, sac, ts

    def test_slot_names(self, multitask_setup):
        env_cls, _, _ = multitask_setup
        assert scenario_slot_names(env_cls, "mt") == [
            "mt/swingup", "mt/balance", "mt/spin",
        ]
        assert scenario_slot_names(HurdleRunnerJax, "hr") == ["hr"]

    def test_task_slot_policy_pins_onehot(self, multitask_setup):
        env_cls, sac, ts = multitask_setup
        base_obs = jnp.linspace(-1.0, 1.0, 3)[None, :]
        for task in range(env_cls.n_tasks):
            policy = TaskSlotPolicy(sac.actor_def, env_cls.n_tasks, task)
            a_slot, _ = policy.apply(
                ts.actor_params, base_obs, deterministic=True,
                with_logprob=False,
            )
            full = jnp.concatenate(
                [base_obs, jax.nn.one_hot(task, 3)[None, :]], axis=-1
            )
            a_full, _ = sac.actor_def.apply(
                ts.actor_params, full, deterministic=True, with_logprob=False
            )
            np.testing.assert_array_equal(
                np.asarray(a_slot), np.asarray(a_full)
            )

    def test_per_task_slots_on_registry(self, multitask_setup):
        from torch_actor_critic_tpu.serve.registry import ModelRegistry

        env_cls, sac, ts = multitask_setup
        registry = ModelRegistry()
        names = register_scenario_slots(
            registry, env_cls, sac.actor_def, name="pendulum-multitask",
            params=ts.actor_params, max_batch=4, warmup=False,
        )
        assert set(names) == set(registry.slots())
        assert len(names) == env_cls.n_tasks
        for slot in names:
            engine, params, generation = registry.acquire(slot)
            assert generation == 0
            act = engine.act(
                params, jnp.zeros((2, 3)), key=jax.random.key(1),
                deterministic=False,
            )
            assert np.asarray(act).shape == (2, env_cls.act_dim)
            assert np.all(np.isfinite(np.asarray(act)))
        registry.close()

    def test_single_slot_scenarios(self):
        from torch_actor_critic_tpu.serve.registry import ModelRegistry

        cfg = small_config()
        env_cls, sac = _wrap_and_build(multi_agent_pendulum(2), cfg)
        ts = sac.init_state(jax.random.key(0), jnp.zeros(env_cls.obs_dim))
        registry = ModelRegistry()
        names = register_scenario_slots(
            registry, env_cls, sac.actor_def, name="multi-pendulum-2",
            params=ts.actor_params, max_batch=4, warmup=False,
        )
        assert names == ["multi-pendulum-2"]
        engine, params, _ = registry.acquire(names[0])
        act = engine.act(
            params, jnp.zeros((1, env_cls.obs_dim)), key=jax.random.key(2),
            deterministic=False,
        )
        assert np.asarray(act).shape == (1, env_cls.act_dim)
        registry.close()


# -------------------------------------------------- analysis/cost wiring


class TestAnalysisWiring:
    def test_scenario_epoch_is_a_registered_entry_point(self):
        from torch_actor_critic_tpu.analysis.reachability import ENTRY_POINTS

        assert ScenarioOnDeviceLoop.epoch_cost_name == "train/scenario_epoch"
        suffix, builder = ENTRY_POINTS["train/scenario_epoch"]
        assert suffix == "scenarios/loop.py"
        assert builder == "ScenarioOnDeviceLoop._build_epoch"

    def test_scenario_epoch_registers_with_cost_registry(self):
        from torch_actor_critic_tpu.telemetry.costmodel import CostRegistry

        cfg = small_config()
        env_cls, sac = _wrap_and_build(short_env(PendulumMultiTaskJax), cfg)
        loop = ScenarioOnDeviceLoop(sac, env_cls, n_envs=4)
        ts, buf, es, key = loop.init(jax.random.key(0), buffer_capacity=3000)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            (ts, buf, es, key),
        )
        ts, buf, es, key, _ = loop.epoch(
            ts, buf, es, key, steps=10, update_every=10, warmup=True
        )
        fn = loop.epoch_jit(10, 10, True)
        assert fn is not None
        registry = CostRegistry()
        registry.register_jit(loop.epoch_cost_name, fn, *abstract)
        cost = registry.get(loop.epoch_cost_name)
        assert cost is not None and cost["flops"] > 0
