"""End-to-end trainer, checkpoint/resume, tracking and CLI tests.

Covers the layer the reference leaves untested (train loop,
checkpointing — SURVEY.md §4 "Not tested") with a tiny Pendulum-v1
config on a 2-device slice of the CPU mesh.
"""

import json

import jax
import numpy as np
import pytest

from torch_actor_critic_tpu.parallel import make_mesh
from torch_actor_critic_tpu.sac.trainer import Trainer
from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
from torch_actor_critic_tpu.utils.config import SACConfig
from torch_actor_critic_tpu.utils.tracking import Tracker

TINY = dict(
    hidden_sizes=(32, 32),
    batch_size=32,
    epochs=2,
    steps_per_epoch=60,
    start_steps=20,
    update_after=20,
    update_every=10,
    buffer_size=2000,
    max_ep_len=200,
)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    root = tmp_path_factory.mktemp("runs")
    cfg = SACConfig(**TINY)
    tracker = Tracker(experiment="test", root=root)
    ckpt = Checkpointer(tracker.artifact_path("checkpoints"))
    tr = Trainer(
        "Pendulum-v1", cfg, mesh=make_mesh(dp=2), tracker=tracker, checkpointer=ckpt
    )
    metrics = tr.train()
    return tr, tracker, metrics, root


def test_training_progresses(trained):
    tr, _, metrics, _ = trained
    assert int(tr.state.step) == 100  # 10 update windows x 10 steps
    np.testing.assert_array_equal(np.asarray(tr.buffer.size), [120, 120])
    for k in ("episode_length", "reward", "loss_q", "loss_pi"):
        assert k in metrics  # reference metric names (algorithm.py:285-290)
    assert np.isfinite(metrics["loss_q"])


def test_tracker_wrote_metrics_and_params(trained):
    _, tracker, _, _ = trained
    rows = tracker.metrics()
    assert len(rows) == 2  # one per epoch
    assert "loss_q" in rows[0]


def test_evaluate(trained):
    tr, _, _, _ = trained
    ev = tr.evaluate(episodes=2, deterministic=True)
    assert ev["ep_len_mean"] == 200.0  # Pendulum never terminates early
    assert np.isfinite(ev["ep_ret_mean"])


@pytest.mark.parametrize("deterministic", [True, False])
def test_evaluate_seeded_is_reproducible(trained, deterministic):
    """VERDICT r2 weak #3: the product eval surface must be as
    reproducible as the parity scripts — same seed, same returns, for
    both the deterministic policy (env resets seeded) and the
    stochastic one (acting PRNG re-keyed from the seed too)."""
    tr, _, _, _ = trained
    a = tr.evaluate(episodes=2, deterministic=deterministic, seed=123)
    b = tr.evaluate(episodes=2, deterministic=deterministic, seed=123)
    assert a == b
    c = tr.evaluate(episodes=2, deterministic=deterministic, seed=124)
    assert c["ep_ret_mean"] != a["ep_ret_mean"]  # seed actually reaches the env


def test_checkpoint_resume_full_state(trained):
    tr, tracker, _, root = trained
    ckpt2 = Checkpointer(tracker.artifact_path("checkpoints"))
    cfg = SACConfig(**TINY)
    tr2 = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=2), checkpointer=ckpt2)
    start = tr2.restore()
    # Saves happen at e=0 (e % save_every == 0) AND at the final epoch
    # e=1 (short runs always checkpoint their last epoch); restore picks
    # the latest, so resume continues exactly where training stopped.
    assert start == 2
    # Full state round-trips: a real (non-init) step counter, params
    # distinct from fresh init, and a non-empty restored buffer —
    # everything the reference's load_session loses (SURVEY.md §3.5).
    assert 0 < int(tr2.state.step) <= int(tr.state.step)
    fresh = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=2))
    a = jax.tree_util.tree_leaves(tr2.state.actor_params)[0]
    b = jax.tree_util.tree_leaves(fresh.state.actor_params)[0]
    assert not np.allclose(np.asarray(a), np.asarray(b))
    assert int(tr2.buffer.size[0]) > 0  # buffer restored, not empty


def test_weights_only_restore(trained):
    tr, tracker, _, _ = trained
    ckpt = Checkpointer(tracker.artifact_path("checkpoints"))
    cfg = SACConfig(**TINY)
    tr2 = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=1), checkpointer=ckpt)
    tr2.restore(include_buffer=False)
    assert int(tr2.buffer.size[0]) == 0  # untouched


@pytest.mark.parametrize("dp", [1, 2])
def test_warmup_counters_scale_with_envs(dp):
    """PARITY.md §counters: `step` is the per-env lockstep counter (the
    reference's per-rank step), so warmup data volume is
    start_steps × n_envs and the first grad step happens after
    update_after per-env steps at every dp."""
    cfg = SACConfig(
        hidden_sizes=(16, 16),
        batch_size=16,
        epochs=1,
        steps_per_epoch=30,
        start_steps=10,
        update_after=10,
        update_every=10,
        buffer_size=1000,
        max_ep_len=100,
    )
    tr = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=dp))
    tr.train()
    # 30 lockstep steps x dp envs transitions total, dp per-device shards
    np.testing.assert_array_equal(np.asarray(tr.buffer.size), [30] * dp)
    # windows at step 20 and 30 ran bursts (step 10 <= update_after):
    # 2 x update_every grad steps regardless of dp.
    assert int(tr.state.step) == 20
    tr.close()


def test_seeded_eval_is_pool_width_invariant():
    """VERDICT r3 #9: evaluation round-robins the whole env pool. The
    concurrent protocol must not change WHAT is measured — episode i
    still resets with seed+i, so under a deterministic policy the
    seeded eval of the same params is the same set of trajectories at
    any pool width (3 episodes over 2 slots exercises the round-robin
    handoff). Equality is up to batch-width float reassociation: the
    actor's matmul reduces a width-1 and a width-2 batch in different
    orders, so returns agree to ~1e-9, not bitwise."""
    cfg = SACConfig(**TINY)
    evs = []
    for dp in (1, 2):
        tr = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=dp), seed=0)
        evs.append(tr.evaluate(episodes=3, deterministic=True, seed=5))
        tr.close()
    assert evs[0]["ep_len_mean"] == evs[1]["ep_len_mean"]
    assert evs[0]["ep_ret_mean"] == pytest.approx(
        evs[1]["ep_ret_mean"], rel=1e-6
    )
    assert evs[0]["ep_ret_std"] == pytest.approx(
        evs[1]["ep_ret_std"], rel=1e-6
    )


def test_fixed_alpha_dm_control_warns(caplog):
    """VERDICT r3 #7: dm_control's [0,1]-per-step rewards are swamped
    by the reference-default fixed alpha=0.2 (measured 0.5 vs 228.0 on
    dm:cheetah:run at 100k — PARITY.md); the trainer must convert that
    silent failure into a guided one. The reference fails silently
    (ref main.py:148 fixed alpha, no diagnostics)."""
    import logging

    pytest.importorskip("dm_control")
    cfg = SACConfig(**TINY)
    with caplog.at_level(logging.WARNING, logger="torch_actor_critic_tpu"):
        tr = Trainer("dm:cartpole:balance", cfg, mesh=make_mesh(dp=1))
        tr.close()
    assert any("learn-alpha" in r.getMessage() for r in caplog.records)

    # Guided configurations stay quiet: learned temperature, or TD3
    # (no entropy term at all), or a gymnasium-scale reward env.
    for env, overrides in (
        ("dm:cartpole:balance", {"learn_alpha": True}),
        ("dm:cartpole:balance", {"algorithm": "td3"}),
        ("Pendulum-v1", {}),
        # Visual but NOT dm_control: gymnasium-scale rewards, no warning
        ("PixelPendulum-v0", {
            "filters": (8, 16), "kernel_sizes": (4, 3), "strides": (2, 2),
            "cnn_dense_size": 32,
        }),
    ):
        caplog.clear()
        with caplog.at_level(
            logging.WARNING, logger="torch_actor_critic_tpu"
        ):
            tr = Trainer(env, SACConfig(**{**TINY, **overrides}),
                         mesh=make_mesh(dp=1))
            tr.close()
        assert not any(
            "learn-alpha" in r.getMessage() for r in caplog.records
        ), (env, overrides)


def test_dm_control_cheetah_run_trains():
    """BASELINE config 3: dm_control cheetah-run through the gym-style
    wrapper, end-to-end short training (the reference reaches dm tasks
    via its env registry; ours via the dm:domain:task scheme)."""
    pytest.importorskip("dm_control")
    cfg = SACConfig(
        hidden_sizes=(32, 32),
        batch_size=16,
        epochs=1,
        steps_per_epoch=60,
        start_steps=20,
        update_after=20,
        update_every=20,
        buffer_size=500,
        max_ep_len=200,
    )
    tr = Trainer("dm:cheetah:run", cfg, mesh=make_mesh(dp=1))
    try:
        metrics = tr.train()
        assert int(tr.state.step) == 40
        assert np.isfinite(metrics["loss_q"])
        assert int(tr.buffer.size[0]) == 60
    finally:
        tr.close()


def test_eight_way_dp_halfcheetah_trains():
    """BASELINE config 4: 8-way data-parallel HalfCheetah — 8 MuJoCo
    envs in lockstep feeding 8 replay shards, pmean-averaged bursts on
    the full 8-device mesh (the reference's `mpirun -np 8` analogue)."""
    pytest.importorskip("mujoco")
    cfg = SACConfig(
        hidden_sizes=(32, 32),
        batch_size=16,
        epochs=1,
        steps_per_epoch=40,
        start_steps=10,
        update_after=10,
        update_every=10,
        buffer_size=2000,
        max_ep_len=1000,
    )
    tr = Trainer("HalfCheetah-v5", cfg, mesh=make_mesh(dp=8))
    try:
        assert tr.n_envs == 8
        metrics = tr.train()
        assert int(tr.state.step) == 30
        np.testing.assert_array_equal(np.asarray(tr.buffer.size), [40] * 8)
        assert np.isfinite(metrics["loss_q"])
        leaf = jax.tree_util.tree_leaves(tr.state.actor_params)[0]
        assert leaf.sharding.is_fully_replicated
    finally:
        tr.close()


def test_same_seed_runs_are_bit_identical():
    """Full-run reproducibility: two trainers with the same seed must
    produce byte-identical params and replay contents (explicit PRNG
    keys + seeded envs + deterministic XLA; the reference can't promise
    this — its per-rank numpy/torch RNG state isn't part of any
    contract)."""
    cfg = SACConfig(
        hidden_sizes=(16, 16),
        batch_size=16,
        epochs=1,
        steps_per_epoch=40,
        start_steps=10,
        update_after=10,
        update_every=10,
        buffer_size=500,
        max_ep_len=100,
    )

    def run():
        tr = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=2), seed=7)
        try:
            tr.train()
            return (
                jax.tree_util.tree_map(np.asarray, tr.state.actor_params),
                jax.tree_util.tree_map(np.asarray, tr.state.critic_params),
                np.asarray(tr.buffer.data.states),
                np.asarray(tr.buffer.data.rewards),
            )
        finally:
            tr.close()

    a, b = run(), run()
    for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b), strict=True
    ):
        np.testing.assert_array_equal(x, y)


def test_train_cli_smoke(tmp_path):
    from torch_actor_critic_tpu.train import main

    metrics = main(
        [
            "--environment",
            "Pendulum-v1",
            "--devices",
            "1",
            "--runs-root",
            str(tmp_path),
            "--epochs",
            "1",
            "--steps-per-epoch",
            "40",
            "--start-steps",
            "10",
            "--update-after",
            "10",
            "--update-every",
            "10",
            "--batch-size",
            "16",
            "--buffer-size",
            "500",
            "--hidden-sizes",
            "16,16",
            "--max-ep-len",
            "100",
        ]
    )
    assert "loss_q" in metrics
    # run directory with params + metrics + checkpoint exists
    exp_dir = tmp_path / "Default"
    run_dirs = list(exp_dir.iterdir())
    assert len(run_dirs) == 1
    params = json.loads((run_dirs[0] / "params.json").read_text())
    assert params["environment"] == "Pendulum-v1"
    assert params["config"]["batch_size"] == 16


def test_run_agent_cli_smoke(tmp_path):
    from torch_actor_critic_tpu.run_agent import main as eval_main
    from torch_actor_critic_tpu.train import main as train_main

    train_main(
        [
            "--environment",
            "Pendulum-v1",
            "--devices",
            "1",
            "--runs-root",
            str(tmp_path),
            "--epochs",
            "1",
            "--steps-per-epoch",
            "30",
            "--start-steps",
            "10",
            "--update-after",
            "10",
            "--update-every",
            "10",
            "--batch-size",
            "16",
            "--buffer-size",
            "500",
            "--hidden-sizes",
            "16,16",
            "--max-ep-len",
            "100",
        ]
    )
    run_id = next((tmp_path / "Default").iterdir()).name
    metrics = eval_main(
        [
            "--run",
            run_id,
            "--runs-root",
            str(tmp_path),
            "--episodes",
            "1",
            "--headless",
        ]
    )
    assert np.isfinite(metrics["ep_ret_mean"])


def test_actor_param_lag_trains_and_keeps_mirror_warm():
    """actor_param_lag=True: the mirror is refreshed from PRE-burst
    params at dispatch time (one window of staleness, full env/learner
    overlap) instead of invalidated — training must still progress and
    the mirror must be populated after a burst, not None. Evaluation
    resets it to the current params."""
    cfg = SACConfig(**TINY, actor_param_lag=True)
    tr = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=1))
    try:
        metrics = tr.train()
        assert np.isfinite(metrics["loss_q"])
        assert tr._host_params is not None  # warm, not invalidated
        # The warm mirror must hold the PRE-final-burst params: equality
        # with the current device params would mean the refresh happens
        # post-burst, re-serializing the env loop on the learner.
        mirror_leaf = jax.tree_util.tree_leaves(tr._host_params)[0]
        device_leaf = np.asarray(
            jax.tree_util.tree_leaves(tr.state.actor_params)[0]
        )
        assert not np.allclose(np.asarray(mirror_leaf), device_leaf)
        ev = tr.evaluate(episodes=1, deterministic=True, seed=7)
        assert np.isfinite(ev["ep_ret_mean"])
    finally:
        tr.close()


def test_actor_param_lag_requires_host_actor():
    with pytest.raises(ValueError, match="actor_param_lag"):
        SACConfig(actor_param_lag=True, host_actor=False)


def test_utd_scales_updates_per_window():
    """UTD (REDQ-style update-to-data ratio, extension): utd=2 doubles
    the gradient steps each update window runs; the reference is pinned
    at 1 (ref sac/algorithm.py:273-283)."""
    cfg = SACConfig(
        hidden_sizes=(16, 16), batch_size=16, epochs=1, steps_per_epoch=40,
        start_steps=10, update_after=10, update_every=10, buffer_size=500,
        max_ep_len=100, utd=2.0,
    )
    assert cfg.updates_per_window == 20
    tr = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=1))
    tr.train()
    # Windows end at steps 9/19/29/39; bursts run once step > 10:
    # 3 bursts x 20 updates.
    assert int(tr.state.step) == 60
    tr.close()


def test_utd_validation():
    with pytest.raises(ValueError, match="no gradient steps"):
        SACConfig(update_every=10, utd=0.01)
    assert SACConfig(update_every=10, utd=0.5).updates_per_window == 5
