"""MLflow mirroring smoke test (VERDICT r4 #9).

mlflow is not installed in this image, so the mirror is exercised
against a faithful stub exposing the exact four entry points the
Tracker calls (``set_experiment``, ``start_run``, ``log_params``,
``log_metrics`` — the reference's usage surface, ref
``main.py:132-138``). The point is pinning the Tracker side of the
contract: every param/metric logged to the file tracker reaches the
mirror with the same keys, values, and step.
"""

import sys
import types

from torch_actor_critic_tpu.utils.tracking import Tracker


def _fake_mlflow():
    calls = {"experiments": [], "runs": [], "params": [], "metrics": []}
    mod = types.ModuleType("mlflow")
    mod.set_experiment = lambda name: calls["experiments"].append(name)
    mod.start_run = lambda run_name=None: calls["runs"].append(run_name)
    mod.log_params = lambda p: calls["params"].append(dict(p))
    mod.log_metrics = lambda m, step: calls["metrics"].append((dict(m), step))
    return mod, calls


def test_tracker_mirrors_params_and_metrics(tmp_path, monkeypatch):
    mod, calls = _fake_mlflow()
    monkeypatch.setitem(sys.modules, "mlflow", mod)
    tr = Tracker(experiment="exp", root=tmp_path, mirror_mlflow=True)
    assert calls["experiments"] == ["exp"]
    assert calls["runs"] == [tr.run_id]

    tr.log_params({"lr": 3e-4, "batch_size": 64})
    tr.log_metrics({"loss_q": 1.5, "reward": -120.0}, step=3)
    tr.log_metrics({"loss_q": 1.0}, step=4)

    # The file tracker and the mirror saw the SAME stream.
    assert calls["params"] == [{"lr": 3e-4, "batch_size": 64}]
    assert calls["metrics"] == [
        ({"loss_q": 1.5, "reward": -120.0}, 3),
        ({"loss_q": 1.0}, 4),
    ]
    rows = tr.metrics()
    assert rows[0]["loss_q"] == 1.5 and rows[0]["step"] == 3
    assert tr.params() == {"lr": 3e-4, "batch_size": 64}


def test_tracker_survives_missing_mlflow(tmp_path, monkeypatch):
    """mirror_mlflow=True must degrade to file-only when mlflow is
    absent (this image) — same run, no crash, no mirror."""
    monkeypatch.setitem(sys.modules, "mlflow", None)  # import -> ImportError
    tr = Tracker(experiment="exp", root=tmp_path, mirror_mlflow=True)
    assert tr._mlflow is None
    tr.log_params({"lr": 1.0})
    tr.log_metrics({"x": 2.0}, step=0)
    assert tr.metrics()[0]["x"] == 2.0
