"""Resilience tests: every recovery path proven end-to-end on CPU.

Each production fault class is injected into a REAL Trainer through
``resilience/faultinject.py`` (ISSUE 2) and the recovery is asserted,
not hoped for:

- NaN batch   -> divergence sentinel -> rollback to last-good -> recovery
- SIGTERM     -> emergency checkpoint -> requeue exit -> bitwise resume
- flaky IO    -> bounded retry; corrupt newest step -> fallback to older
- dead worker -> diagnosed error (with exit code), bounded close()

Synchronization discipline: every injection keys off an exact lockstep
step count (``FaultyEnvPool``) or a joined process — no wall-clock
sleeps anywhere, so nothing here is timing-flaky.
"""

import json
import os
import signal
import time

import jax
import numpy as np
import pytest

from torch_actor_critic_tpu.envs.vec_env import ParallelEnvPool
from torch_actor_critic_tpu.native import load_runtime
from torch_actor_critic_tpu.parallel import make_mesh
from torch_actor_critic_tpu.resilience import (
    REQUEUE_EXIT_CODE,
    DivergenceSentinel,
    Preempted,
    PreemptionGuard,
    TrainingDiverged,
    call_with_retries,
    tree_all_finite,
)
from torch_actor_critic_tpu.resilience.faultinject import (
    FaultyEnvPool,
    corrupt_checkpoint,
    kill_env_worker,
    make_flaky,
)
from torch_actor_critic_tpu.sac.trainer import Trainer
from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
from torch_actor_critic_tpu.utils.config import SACConfig

needs_native = pytest.mark.skipif(
    load_runtime() is None, reason="native runtime unavailable"
)

TINY = dict(
    hidden_sizes=(16, 16),
    batch_size=16,
    epochs=3,
    steps_per_epoch=40,
    start_steps=10,
    update_after=10,
    update_every=10,
    buffer_size=500,
    max_ep_len=100,
    save_every=1,
)


def make_trainer(ckpt_dir, seed=7, dp=1, preemption=None, **over):
    cfg = SACConfig(**{**TINY, **over})
    ck = (
        Checkpointer(ckpt_dir, retry_backoff_s=0.0)
        if ckpt_dir is not None
        else None
    )
    return Trainer(
        "Pendulum-v1",
        cfg,
        mesh=make_mesh(dp=dp),
        checkpointer=ck,
        seed=seed,
        preemption=preemption,
    )


def comparable_state(tr):
    """Every array that defines the learner: full TrainState (PRNG key
    as raw uint32) + the replay ring and its cursors."""
    s = tr.state
    trees = {
        "actor": s.actor_params,
        "critic": s.critic_params,
        "target": s.target_critic_params,
        "pi_opt": s.pi_opt_state,
        "q_opt": s.q_opt_state,
        "log_alpha": s.log_alpha,
        "alpha_opt": s.alpha_opt_state,
        "step": s.step,
        "rng": jax.random.key_data(s.rng),
        "buffer": tr.buffer.data,
        "ptr": tr.buffer.ptr,
        "size": tr.buffer.size,
    }
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(trees)]


# ------------------------------------------------- path 1: NaN -> rollback


def test_nan_batch_rolls_back_and_recovers(tmp_path):
    """A NaN reward mid-epoch-1 must cost exactly one rollback (to the
    sentinel-validated epoch-0 checkpoint) and training must finish
    with finite metrics, finite params and a clean replay ring — the
    reference trains on the poison forever."""
    tr = make_trainer(tmp_path / "ck", epochs=4)
    # Lockstep step 50 is inside epoch 1 (steps 40..79): the epoch-0
    # checkpoint already exists, so rollback has a target.
    tr.pool = FaultyEnvPool(tr.pool).nan_rewards_at(50)
    try:
        metrics = tr.train()
        assert tr.sentinel.total_rollbacks == 1
        assert metrics["rollbacks"] == 1
        assert np.isfinite(metrics["loss_q"])
        assert np.isfinite(metrics["loss_pi"])
        # Rollback restored the ring too: the poisoned rows are gone
        # (a params-only rollback would re-diverge on the next sample).
        assert np.isfinite(np.asarray(tr.buffer.data.rewards)).all()
        assert tree_all_finite(tr.state, tr.buffer.data)
    finally:
        tr.close()


def test_divergence_without_checkpoint_aborts():
    """No checkpointer -> nothing to roll back to: the run must abort
    with a diagnosed TrainingDiverged, not keep training on NaNs."""
    tr = make_trainer(None, epochs=2)
    tr.pool = FaultyEnvPool(tr.pool).nan_rewards_at(5)
    try:
        with pytest.raises(TrainingDiverged, match="no checkpoint"):
            tr.train()
    finally:
        tr.close()


def test_rollback_budget_bounds_consecutive_divergence(tmp_path):
    """Persistent (systematic) divergence must exhaust max_rollbacks
    and abort instead of rolling back forever: NaN injected in two
    consecutive epochs with a budget of one."""
    tr = make_trainer(tmp_path / "ck", epochs=4, max_rollbacks=1)
    tr.pool = (
        FaultyEnvPool(tr.pool).nan_rewards_at(50).nan_rewards_at(90)
    )
    try:
        with pytest.raises(TrainingDiverged, match="consecutive"):
            tr.train()
    finally:
        tr.close()


# --------------------------------- path 2: SIGTERM -> save -> requeue code


def test_sigterm_preemption_saves_and_resume_is_bitwise(tmp_path):
    """The full preemption round-trip with a REAL signal: SIGTERM lands
    mid-epoch-1, the trainer finishes the epoch, checkpoints, and
    raises with the requeue exit code; a resumed run continues and
    finishes with a learner state BITWISE identical to an uninterrupted
    run — epochs are replayable units (epoch-boundary reseeding + the
    checkpointed step counter and acting key)."""
    # Run A: 3 epochs, uninterrupted.
    tra = make_trainer(tmp_path / "a", epochs=3, save_every=10)
    try:
        tra.train()
        ref = comparable_state(tra)
    finally:
        tra.close()

    # Run B: same seed/config; SIGTERM delivered at lockstep step 45
    # (epoch 1). The installed handler only flags; the trainer exits at
    # the epoch boundary after an emergency save.
    guard = PreemptionGuard().install()
    trb = make_trainer(
        tmp_path / "b", epochs=3, save_every=10, preemption=guard
    )
    trb.pool = FaultyEnvPool(trb.pool).call_at(
        45, lambda: os.kill(os.getpid(), signal.SIGTERM)
    )
    try:
        with pytest.raises(Preempted) as ei:
            trb.train()
    finally:
        guard.uninstall()
        trb.close()
    assert ei.value.exit_code == REQUEUE_EXIT_CODE
    assert ei.value.epoch == 1
    meta = trb.checkpointer.peek_meta()
    assert meta["epoch"] == 1
    assert meta["step"] == 80  # epoch boundary: 2 epochs x 40 steps
    assert meta["act_key"]  # the acting stream is part of the state

    # Run C: resume B and train the remaining epoch.
    trc = make_trainer(tmp_path / "b", epochs=1, save_every=10)
    try:
        assert trc.restore() == 2
        assert trc._resume_step == 80  # no warmup redo on resume
        trc.train()
        got = comparable_state(trc)
    finally:
        trc.close()
    for x, y in zip(ref, got, strict=True):
        np.testing.assert_array_equal(x, y)


def test_urgent_preemption_saves_at_window_boundary(tmp_path):
    """A second signal (here the programmatic harness path) must not
    wait for the epoch: the checkpoint lands at the next update-window
    boundary with the mid-epoch step counter, and resume continues
    from it without re-randomizing warmup."""
    guard = PreemptionGuard()  # never installed: API-driven preemption
    tr = make_trainer(
        tmp_path / "ck", epochs=3, save_every=10, preemption=guard
    )
    tr.pool = FaultyEnvPool(tr.pool).call_at(
        52, lambda: guard.request_preemption(urgent=True)
    )
    try:
        with pytest.raises(Preempted) as ei:
            tr.train()
    finally:
        tr.close()
    assert ei.value.urgent
    meta = tr.checkpointer.peek_meta()
    assert meta["epoch"] == 1
    assert meta["step"] == 60  # first window boundary after step 52

    tr2 = make_trainer(tmp_path / "ck", epochs=1, save_every=10)
    try:
        assert tr2.restore() == 2
        assert tr2._resume_step == 60
        m = tr2.train()
        assert np.isfinite(m["loss_q"])
        assert int(tr2.state.step) > 50  # gradient steps continued
    finally:
        tr2.close()


def test_train_cli_maps_preempted_to_requeue_exit_code(tmp_path, monkeypatch):
    """train.py converts Preempted into SystemExit(75) so `make`/
    schedulers can tell *requeue me* from a crash."""
    from torch_actor_critic_tpu import train as train_mod

    def fake_train(self, render=False):
        raise Preempted(epoch=0)

    monkeypatch.setattr(Trainer, "train", fake_train)
    with pytest.raises(SystemExit) as ei:
        train_mod.main(
            [
                "--environment", "Pendulum-v1",
                "--devices", "1",
                "--runs-root", str(tmp_path),
                "--epochs", "1",
                "--steps-per-epoch", "10",
                "--batch-size", "16",
                "--buffer-size", "100",
                "--hidden-sizes", "16,16",
            ]
        )
    assert ei.value.code == REQUEUE_EXIT_CODE


# ------------------------- path 3: checkpoint IO retry / corrupt fallback


def test_checkpoint_save_and_restore_retry_transient_io(tmp_path):
    """Transient OSErrors (network FS hiccups) are absorbed by the
    bounded retry ladder; persistent ones still surface."""
    ck = Checkpointer(
        tmp_path / "ck", retries=2, retry_backoff_s=0.0,
        sleep=lambda s: None, save_buffer=False,
    )
    state = {"w": np.arange(4, dtype=np.float32)}
    ck._mgr.save = make_flaky(ck._mgr.save, failures=2)
    ck.save(0, state, wait=True)  # 2 failures < 3 attempts -> lands
    ck._mgr.restore = make_flaky(ck._mgr.restore, failures=2)
    assert ck.peek_meta(0)["epoch"] == 0
    ck.close()

    ck2 = Checkpointer(
        tmp_path / "ck2", retries=1, retry_backoff_s=0.0,
        sleep=lambda s: None, save_buffer=False,
    )
    ck2._mgr.save = make_flaky(ck2._mgr.save, failures=2)
    with pytest.raises(OSError, match="injected"):
        ck2.save(0, state, wait=True)
    ck2.close()


def test_retry_backoff_is_exponential_and_fnf_gives_up():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert (
        call_with_retries(
            flaky, attempts=3, base_delay_s=0.5, sleep=sleeps.append
        )
        == "ok"
    )
    assert sleeps == [0.5, 1.0]

    def missing():
        raise FileNotFoundError("gone for good")

    with pytest.raises(FileNotFoundError):
        # Deterministic failure: must NOT burn retry attempts on it.
        call_with_retries(
            missing, attempts=3, base_delay_s=0.5, sleep=sleeps.append
        )
    assert sleeps == [0.5, 1.0]  # no additional sleeps


@pytest.mark.parametrize("mode", ["drop-item", "truncate"])
def test_corrupt_newest_checkpoint_falls_back_to_previous(tmp_path, mode):
    """An interrupted/corrupt newest step (simulated exactly as a
    mid-write crash leaves it) must cost one save_every interval, not
    the resume: restore falls back to epoch 0 and training continues."""
    tr = make_trainer(tmp_path / "ck", epochs=2)  # checkpoints 0 and 1
    try:
        tr.train()
    finally:
        tr.close()
    corrupt_checkpoint(tmp_path / "ck", 1, mode=mode)

    tr2 = make_trainer(tmp_path / "ck", epochs=1)
    try:
        assert tr2.restore() == 1  # fell back: resumes AFTER epoch 0
        m = tr2.train()
        assert np.isfinite(m["loss_q"])
    finally:
        tr2.close()


def test_unreadable_meta_is_skipped_by_latest_epoch(tmp_path):
    tr = make_trainer(tmp_path / "ck", epochs=2)
    try:
        tr.train()
    finally:
        tr.close()
    corrupt_checkpoint(tmp_path / "ck", 1, mode="drop-meta")
    ck = Checkpointer(tmp_path / "ck")
    try:
        assert ck.latest_epoch() == 0
        assert ck.peek_meta()["epoch"] == 0
    finally:
        ck.close()


def test_explicit_epoch_never_falls_back(tmp_path):
    """Fallback is a resume (epoch=None) behavior only: a caller that
    pins an epoch asked for THAT state — substituting another would be
    silent corruption."""
    tr = make_trainer(tmp_path / "ck", epochs=2)
    try:
        tr.train()
    finally:
        tr.close()
    corrupt_checkpoint(tmp_path / "ck", 1, mode="drop-item")
    tr2 = make_trainer(tmp_path / "ck", epochs=1)
    try:
        with pytest.raises(Exception):  # noqa: PT011 — orbax's error class
            tr2.restore(epoch=1)
    finally:
        tr2.close()


# -------------------------------- path 4: dead env worker, bounded close


@needs_native
def test_dead_env_worker_is_diagnosed_with_exit_code_and_close_is_bounded():
    pool = ParallelEnvPool(
        "Pendulum-v1", 2, base_seed=0, timeout_s=3, start_method="fork"
    )
    try:
        pool.reset_all()
        code = kill_env_worker(pool, 1)  # SIGKILL + join: death observed
        assert code == -signal.SIGKILL
        with pytest.raises(
            RuntimeError, match=r"worker 1 died \(exitcode -9\)"
        ):
            pool.step(np.zeros((2, 1), np.float32))
    finally:
        t0 = time.monotonic()
        pool.close()
        # Bounded teardown: CLOSE dispatch + joins + escalation, never
        # a blocking wait on the dead worker's ack.
        assert time.monotonic() - t0 < 30.0


@needs_native
def test_env_worker_death_mid_training_surfaces_and_cleans_up():
    """End-to-end: a worker SIGKILLed mid-training must surface as a
    diagnosed RuntimeError from train() (not a deadlock, the
    reference's behavior), and teardown must complete."""
    cfg = SACConfig(
        **{
            **TINY,
            "epochs": 1,
            "parallel_envs": True,
            "env_timeout_s": 3.0,
            "env_start_method": "fork",
        }
    )
    tr = Trainer("Pendulum-v1", cfg, mesh=make_mesh(dp=2))
    tr.pool = FaultyEnvPool(tr.pool).call_at(
        15, lambda: kill_env_worker(tr.pool, 1)
    )
    try:
        with pytest.raises(RuntimeError, match="exitcode"):
            tr.train()
    finally:
        tr.close()


# ----------------------------------------------------------- unit pieces


def test_tree_all_finite_skips_non_inexact_leaves():
    key = jax.random.key(0)
    assert tree_all_finite(
        {"i": np.arange(3), "f": np.ones(3), "k": key, "b": np.array([True])}
    )
    assert not tree_all_finite({"f": np.array([1.0, np.nan])})
    assert not tree_all_finite(np.array([np.inf]))
    assert tree_all_finite()  # vacuously true


def test_sentinel_budget_resets_on_good_interval():
    s = DivergenceSentinel(max_rollbacks=1)
    s.note_divergence()
    s.note_good()  # a finite epoch closes the streak
    s.note_divergence()
    with pytest.raises(TrainingDiverged):
        s.note_divergence()
    assert s.total_rollbacks == 3


def test_guard_signal_escalation():
    prev = signal.getsignal(signal.SIGTERM)
    guard = PreemptionGuard().install()
    try:
        assert not guard.triggered and not guard.urgent
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.triggered and not guard.urgent
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.urgent
    finally:
        guard.uninstall()
    # install/uninstall round-trips the previous handler exactly.
    assert signal.getsignal(signal.SIGTERM) == prev


def test_checkpoint_meta_carries_resume_state(tmp_path):
    """Every checkpoint persists the host-loop state (step counter,
    acting key) alongside the TrainState — JSON-round-trippable."""
    tr = make_trainer(tmp_path / "ck", epochs=1)
    try:
        tr.train()
    finally:
        tr.close()
    meta = Checkpointer(tmp_path / "ck").peek_meta()
    assert meta["step"] == 40
    key = np.asarray(meta["act_key"], dtype=np.uint32)
    assert key.shape == np.asarray(
        jax.random.key_data(jax.random.key(0))
    ).shape
    json.dumps(meta)  # the whole meta stays JSON-serializable


# ---------------------------------------- path 5: lossy actor<->serving link


def test_lossy_link_degrades_actor_and_recovery_rehomes(tmp_path):
    """The decoupled plane's link fault (resilience/faultinject.py
    LossyLink): a dropped-then-recovering actor<->serving link must
    degrade acting to the local snapshot WITHOUT stalling the env loop,
    keep training, and re-home when the link heals — the decoupled
    twin of the env-worker-death path (ISSUE 10; the full matrix lives
    in tests/test_decoupled.py and `make decouple-smoke`)."""
    from torch_actor_critic_tpu.decoupled import DecoupledTrainer
    from torch_actor_critic_tpu.resilience.faultinject import LossyLink

    cfg = SACConfig(**{**TINY, "epochs": 2, "decoupled": True})
    tr = DecoupledTrainer(
        "Pendulum-v1", cfg, mesh=make_mesh(dp=1),
        checkpointer=None, seed=7,
    )
    # Every serving call from lockstep step 15 to ~step 30 dies at the
    # link; the actor's probe cadence re-homes it before the run ends.
    link = LossyLink(tr.client).drop_next(5)
    tr.pool = FaultyEnvPool(tr.pool).call_at(
        15, lambda: setattr(tr.actor, "client", link)
    )
    try:
        metrics = tr.train()
        assert np.isfinite(metrics["loss_q"])
        assert link.drops_injected == 5
        assert tr.actor.degradations_total >= 1
        assert tr.actor.fallback_actions_total >= 1
        assert tr.actor.rehomes_total >= 1
        assert not tr.actor.degraded  # healed link, re-homed actor
        assert tr.staging.conservation_holds()
    finally:
        tr.close()
