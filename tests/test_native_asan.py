"""AddressSanitizer run of the native futex runtime (round-1 verdict:
the ASan build existed but never executed — "a make target, not a
practiced capability"). Builds ``libtacrt_asan.so`` and drives the full
C API (store/load, cross-thread wait_ne wake, wait_all_eq, timeout
paths) in a subprocess running under ``LD_PRELOAD=libasan``, then
asserts ASan stayed silent.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

NATIVE_DIR = (
    Path(__file__).resolve().parent.parent / "torch_actor_critic_tpu" / "native"
)

_EXERCISE = r"""
import ctypes, threading, time
import numpy as np
import sys

lib = ctypes.CDLL(sys.argv[1])
lib.tac_store_wake.argtypes = [ctypes.c_void_p, ctypes.c_int32]
lib.tac_load.argtypes = [ctypes.c_void_p]; lib.tac_load.restype = ctypes.c_int32
lib.tac_wait_ne.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64]
lib.tac_wait_ne.restype = ctypes.c_int
lib.tac_wait_all_eq.argtypes = [
    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
    ctypes.c_int64,
]
lib.tac_wait_all_eq.restype = ctypes.c_int

buf = np.zeros(64, np.int32)
base = buf.ctypes.data

# store/load roundtrip
lib.tac_store_wake(base, 7)
assert lib.tac_load(base) == 7

# timed wait_ne that times out (value stays equal)
assert lib.tac_wait_ne(base, 7, 50) != 0

# cross-thread wake: waiter blocks until the value changes
def waker():
    time.sleep(0.05)
    lib.tac_store_wake(base, 8)
t = threading.Thread(target=waker); t.start()
assert lib.tac_wait_ne(base, 7, 5000) == 0
t.join()
assert lib.tac_load(base) == 8

# wait_all_eq over a strided barrier: release one slot from another
# thread (stride is in int32 ELEMENTS; targets is a parallel array)
n, stride = 4, 16
words = np.zeros(64, np.int32)
targets = np.ones(64, np.int32)
wbase, tbase = words.ctypes.data, targets.ctypes.data
for i in range(n):
    lib.tac_store_wake(wbase + 4 * i * stride, 1)
lib.tac_store_wake(wbase + 4 * 2 * stride, 0)  # slot 2 not acked yet
def release():
    time.sleep(0.05)
    lib.tac_store_wake(wbase + 4 * 2 * stride, 1)
t = threading.Thread(target=release); t.start()
assert lib.tac_wait_all_eq(wbase, tbase, n, stride, 5000) == 0
t.join()

# wait_all_eq timeout path diagnoses the stuck slot: returns -(i+1)
lib.tac_store_wake(wbase + 4 * 3 * stride, 0)
assert lib.tac_wait_all_eq(wbase, tbase, n, stride, 50) == -4

print("ASAN_EXERCISE_OK")
"""


def test_native_runtime_under_asan(tmp_path):
    if not sys.platform.startswith("linux"):
        pytest.skip("linux-only native runtime")
    try:
        libasan = subprocess.run(
            [os.environ.get("CXX", "g++"), "-print-file-name=libasan.so"],
            capture_output=True, text=True,
        ).stdout.strip()
    except FileNotFoundError:
        pytest.skip("C++ toolchain not available")
    if not libasan or not os.path.isabs(libasan):
        pytest.skip("libasan not available")

    asan_so = tmp_path / "libtacrt_asan.so"
    build = subprocess.run(
        [
            os.environ.get("CXX", "g++"), "-O1", "-g", "-Wall", "-fPIC",
            "-std=c++17", "-fsanitize=address", "-shared", "-o", str(asan_so),
            str(NATIVE_DIR / "tac_runtime.cpp"),
        ],
        capture_output=True, text=True,
    )
    assert build.returncode == 0, build.stderr

    script = tmp_path / "exercise.py"
    script.write_text(_EXERCISE)
    env = dict(os.environ)
    env.update(
        {
            "LD_PRELOAD": libasan,
            # CPython itself leaks interned objects by design; leak
            # checking would flag the interpreter, not our runtime.
            "ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
        }
    )
    proc = subprocess.run(
        [sys.executable, str(script), str(asan_so)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert "ASAN_EXERCISE_OK" in out, out
    assert "AddressSanitizer" not in out, out
