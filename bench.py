"""Headline benchmark: SAC gradient-steps/sec on one TPU chip.

BASELINE.md: the reference publishes no numbers, so the measured
baseline is a PyTorch-CPU implementation of the same update at the
reference run configuration (alpha=0.2 fixed, gamma=0.99, polyak=0.995,
batch 64, hidden [256,256], lr 3e-4, ``torch.set_num_threads(2)`` as in
ref ``main.py:130``) on HalfCheetah-v3 dimensions (obs 17, act 6).

Prints ONE JSON line:
    {"metric": "sac_grad_steps_per_sec", "value": N, "unit":
     "steps/sec", "vs_baseline": ratio_vs_torch_cpu}

The TPU number is measured through the real training path — the fused
``update_burst`` (push + 50 sampled gradient steps per dispatch) over
the HBM replay buffer, exactly what the trainer runs.
"""

import json
import time

import numpy as np

OBS_DIM, ACT_DIM = 17, 6
BATCH = 64
HIDDEN = (256, 256)
BURST = 50


def bench_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.buffer import init_replay_buffer, push
    from torch_actor_critic_tpu.core.types import Batch
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(batch_size=BATCH, hidden_sizes=HIDDEN)
    sac = SAC(cfg, Actor(act_dim=ACT_DIM, hidden_sizes=HIDDEN), DoubleCritic(hidden_sizes=HIDDEN), ACT_DIM)
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    buf = init_replay_buffer(
        1_000_000, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32), ACT_DIM
    )

    def chunk(key, n=BURST):
        ks = jax.random.split(jax.random.key(key), 5)
        return Batch(
            states=jax.random.normal(ks[0], (n, OBS_DIM)),
            actions=jnp.tanh(jax.random.normal(ks[1], (n, ACT_DIM))),
            rewards=jax.random.normal(ks[2], (n,)),
            next_states=jax.random.normal(ks[3], (n, OBS_DIM)),
            done=jnp.zeros((n,)),
        )

    buf = jax.jit(push, donate_argnums=(0,))(buf, chunk(1, 5000))
    burst = jax.jit(sac.update_burst, static_argnums=(3,), donate_argnums=(0, 1))

    # compile + warmup
    state, buf, m = burst(state, buf, chunk(2), BURST)
    jax.block_until_ready(m)

    n_bursts = 60
    t0 = time.perf_counter()
    for i in range(n_bursts):
        state, buf, m = burst(state, buf, chunk(10 + i), BURST)
    jax.block_until_ready(m)
    dt = time.perf_counter() - t0
    return n_bursts * BURST / dt


def bench_torch_cpu() -> float:
    """Reference-style torch-CPU SAC update (independent implementation
    of the same math: twin-critic Bellman MSE + squashed-Gaussian policy
    loss + polyak), timed per gradient step incl. uniform replay
    sampling — the measured stand-in for the unpublished reference
    baseline."""
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    torch.set_num_threads(2)  # ref main.py:130

    def mlp(sizes, out_dim):
        layers, prev = [], sizes[0]
        for h in sizes[1:]:
            layers += [nn.Linear(prev, h), nn.ReLU()]
            prev = h
        layers.append(nn.Linear(prev, out_dim))
        return nn.Sequential(*layers)

    class TorchActor(nn.Module):
        def __init__(self):
            super().__init__()
            # Linear(17,256)+ReLU+Linear(256,256); forward adds the
            # second ReLU — a 2-hidden trunk matching the JAX Actor.
            self.trunk = mlp([OBS_DIM, HIDDEN[0]], HIDDEN[1])
            self.mu = nn.Linear(HIDDEN[-1], ACT_DIM)
            self.log_std = nn.Linear(HIDDEN[-1], ACT_DIM)

        def forward(self, obs):
            h = F.relu(self.trunk(obs))
            mu, log_std = self.mu(h), torch.clip(self.log_std(h), -20, 2)
            std = torch.exp(log_std)
            u = mu + std * torch.randn_like(mu)
            a = torch.tanh(u)
            logp = torch.distributions.Normal(mu, std).log_prob(u).sum(-1)
            logp = logp - (2 * (np.log(2) - u - F.softplus(-2 * u))).sum(-1)
            return a, logp

    actor = TorchActor()
    critics = [mlp([OBS_DIM + ACT_DIM, *HIDDEN], 1) for _ in range(2)]
    targets = [mlp([OBS_DIM + ACT_DIM, *HIDDEN], 1) for _ in range(2)]
    for c, t in zip(critics, targets):
        t.load_state_dict(c.state_dict())
    pi_opt = torch.optim.Adam(actor.parameters(), lr=3e-4)
    q_opt = torch.optim.Adam(
        [p for c in critics for p in c.parameters()], lr=3e-4
    )

    n = 100_000
    data = {
        "s": torch.randn(n, OBS_DIM),
        "a": torch.tanh(torch.randn(n, ACT_DIM)),
        "r": torch.randn(n),
        "s2": torch.randn(n, OBS_DIM),
        "d": torch.zeros(n),
    }

    def q_of(nets, s, a):
        x = torch.cat([s, a], -1)
        return [net(x).squeeze(-1) for net in nets]

    def step():
        idx = torch.randint(0, n, (BATCH,))
        s, a, r, s2, d = (data[k][idx] for k in ("s", "a", "r", "s2", "d"))
        with torch.no_grad():
            a2, logp2 = actor(s2)
            q_t = torch.min(*q_of(targets, s2, a2))
            backup = r + 0.99 * (1 - d) * (q_t - 0.2 * logp2)
        q1, q2 = q_of(critics, s, a)
        loss_q = ((q1 - backup) ** 2).mean() + ((q2 - backup) ** 2).mean()
        q_opt.zero_grad(); loss_q.backward(); q_opt.step()

        for c in critics:
            for p in c.parameters():
                p.requires_grad_(False)
        pi, logp = actor(s)
        loss_pi = (0.2 * logp - torch.min(*q_of(critics, s, pi))).mean()
        pi_opt.zero_grad(); loss_pi.backward(); pi_opt.step()
        for c in critics:
            for p in c.parameters():
                p.requires_grad_(True)

        with torch.no_grad():
            for c, t in zip(critics, targets):
                for pc, pt in zip(c.parameters(), t.parameters()):
                    pt.mul_(0.995).add_(0.005 * pc)

    for _ in range(20):  # warmup
        step()
    n_steps = 300
    t0 = time.perf_counter()
    for _ in range(n_steps):
        step()
    return n_steps / (time.perf_counter() - t0)


def main():
    torch_sps = bench_torch_cpu()
    tpu_sps = bench_tpu()
    print(
        json.dumps(
            {
                "metric": "sac_grad_steps_per_sec",
                "value": round(tpu_sps, 1),
                "unit": "steps/sec",
                "vs_baseline": round(tpu_sps / torch_sps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
