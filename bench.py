"""Headline benchmark: SAC gradient-steps/sec on one TPU chip.

BASELINE.md: the reference publishes no numbers, so the measured
baseline is a PyTorch-CPU implementation of the same update at the
reference run configuration (alpha=0.2 fixed, gamma=0.99, polyak=0.995,
batch 64, hidden [256,256], lr 3e-4, ``torch.set_num_threads(2)`` as in
ref ``main.py:130``) on HalfCheetah-v3 dimensions (obs 17, act 6).

Prints exactly ONE JSON line on stdout:
    {"metric": "sac_grad_steps_per_sec", "value": N, "unit":
     "steps/sec", "vs_baseline": ratio_vs_torch_cpu, ...}
Extra keys: backend, device_kind, mfu, flops_per_step, sweep (batch/
width scaling), on_device (fused env+update loop throughput), and —
on any failure — "error"/"diagnostics" instead of a silent traceback.

Robustness contract (round-2 hardening):
  * The accelerator backend is preflighted in a SUBPROCESS with a
    bounded timeout and retry/backoff — a hung TPU plugin (the round-1
    failure mode: "Unable to initialize backend 'axon'") cannot wedge
    the parent, which falls back to CPU and still emits a line.
  * The TPU benchmark runs BEFORE the torch baseline so an accelerator
    number is recorded even if the baseline path breaks.
  * Every stage is individually guarded; main() never raises and
    always exits 0 with a parseable JSON line.

The TPU number is measured through the real training path — the fused
``update_burst`` (push + 50 sampled gradient steps per dispatch) over
the HBM replay buffer, exactly what the trainer runs.
"""

import json
import os
import subprocess
import sys
import time

OBS_DIM, ACT_DIM = 17, 6
BATCH = 64
HIDDEN = (256, 256)
BURST = 50

# Pinned fallback: reference-style torch-CPU SAC measured on this image
# (2 threads, ref main.py:130 config) on 2026-07-29. Used for
# vs_baseline only if the live baseline measurement fails.
TORCH_CPU_FALLBACK_SPS = 143.1

# Peak bf16 FLOP/s per chip by TPU generation (public figures); MFU is
# reported against the matching entry (override: TAC_PEAK_FLOPS env).
PEAK_FLOPS_BY_KIND = [
    ("v6", 918e12),
    ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# The axon sitecustomize re-registers "axon,cpu" over JAX_PLATFORMS at
# jax import, so a CPU probe/fallback must force the platform via
# jax.config AFTER import but BEFORE backend init (same countermeasure
# as tests/conftest.py).
_PROBE_SRC = """
import json, time, sys
t0 = time.time()
import jax, jax.numpy as jnp
if len(sys.argv) > 1 and sys.argv[1] == "cpu":
    jax.config.update("jax_platforms", "cpu")
devs = jax.devices()
x = jnp.ones((256, 256), jnp.float32)
assert float((x @ x)[0, 0]) == 256.0  # host fetch = true execution barrier
print(json.dumps({
    "platform": devs[0].platform,
    "device_kind": devs[0].device_kind,
    "n_devices": len(devs),
    "init_seconds": round(time.time() - t0, 1),
}))
"""


def _ensure_platform(platform):
    """Force the chosen platform in-process before any backend init."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def preflight_backend():
    """Probe the default (accelerator) backend in a subprocess with
    retry/backoff; on persistent failure probe CPU. Returns
    (info_dict, diagnostics)."""
    diags = []
    attempts = [(90, 10), (120, 20), (150, 0)]
    if os.environ.get("TAC_BENCH_PLATFORM") == "cpu":
        attempts = []  # operator override: skip straight to CPU
    for attempt, (timeout_s, backoff_s) in enumerate(attempts):
        try:
            t0 = time.time()
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if proc.returncode == 0:
                info = json.loads(proc.stdout.strip().splitlines()[-1])
                log(f"preflight ok: {info}")
                return info, diags
            diags.append({
                "attempt": attempt, "rc": proc.returncode,
                "stderr_tail": proc.stderr[-500:],
                "elapsed": round(time.time() - t0, 1),
            })
            log(f"preflight attempt {attempt} rc={proc.returncode}")
        except subprocess.TimeoutExpired:
            diags.append({"attempt": attempt, "error": f"timeout after {timeout_s}s"})
            log(f"preflight attempt {attempt} timed out ({timeout_s}s)")
        except Exception as e:  # noqa: BLE001 — preflight must not raise
            diags.append({"attempt": attempt, "error": repr(e)})
        if backoff_s:
            time.sleep(backoff_s)

    log("accelerator preflight failed; falling back to CPU backend")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC, "cpu"],
            capture_output=True, text=True, timeout=120,
        )
        info = json.loads(proc.stdout.strip().splitlines()[-1])
        log(f"cpu fallback preflight ok: {info}")
    except Exception as e:  # noqa: BLE001
        diags.append({"cpu_fallback_error": repr(e)})
        info = {"platform": "none", "device_kind": "none", "n_devices": 0}
    return info, diags


def sac_flops_per_step(batch=BATCH, hidden=HIDDEN, obs=OBS_DIM, act=ACT_DIM):
    """Analytic FLOPs for one SAC gradient step (critic+policy update),
    dense matmul MACs x2, batch-scaled. Backward through a layer costs
    ~2x its forward; the frozen-critic pass in the policy loss only
    needs input grads (~1x forward extra). Elementwise/Adam/polyak
    terms are negligible and omitted."""
    def mlp_macs(sizes):
        return sum(a * b for a, b in zip(sizes[:-1], sizes[1:]))

    actor = mlp_macs([obs, *hidden]) + 2 * hidden[-1] * act       # trunk + mu/log_std heads
    critic = 2 * mlp_macs([obs + act, *hidden, 1])                # twin Q
    macs = (
        actor          # pi(s') for the backup (no grad)
        + critic       # target twin fwd
        + 3 * critic   # critic twin fwd+bwd
        + 3 * actor    # actor fwd+bwd (policy loss)
        + 2 * critic   # critic fwd + input-only bwd (frozen)
    )
    return 2 * batch * macs


def _make_bench_fn(obs_dim, act_dim, hidden, batch, capacity=1_000_000,
                   compute_dtype="float32"):
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.buffer import init_replay_buffer, push
    from torch_actor_critic_tpu.core.types import Batch
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(
        batch_size=batch, hidden_sizes=hidden, compute_dtype=compute_dtype
    )
    dt = cfg.model_dtype
    sac = SAC(cfg, Actor(act_dim=act_dim, hidden_sizes=hidden, dtype=dt),
              DoubleCritic(hidden_sizes=hidden, dtype=dt), act_dim)
    state = sac.init_state(jax.random.key(0), jnp.zeros((obs_dim,)))
    buf = init_replay_buffer(
        capacity, jax.ShapeDtypeStruct((obs_dim,), jnp.float32), act_dim
    )

    def chunk(key, n=BURST):
        ks = jax.random.split(jax.random.key(key), 5)
        return Batch(
            states=jax.random.normal(ks[0], (n, obs_dim)),
            actions=jnp.tanh(jax.random.normal(ks[1], (n, act_dim))),
            rewards=jax.random.normal(ks[2], (n,)),
            next_states=jax.random.normal(ks[3], (n, obs_dim)),
            done=jnp.zeros((n,)),
        )

    buf = jax.jit(push, donate_argnums=(0,))(buf, chunk(1, 5000))
    burst = jax.jit(sac.update_burst, static_argnums=(3,), donate_argnums=(0, 1))

    from torch_actor_critic_tpu.utils.sync import drain

    state, buf, m = burst(state, buf, chunk(2), BURST)  # compile + warmup
    drain(m["loss_q"])

    def run(n_bursts):
        # Drain with a host fetch (utils/sync.py): each burst chains
        # through the donated (state, buf), so fetching the last burst's
        # loss forces the whole sequence to execute. block_until_ready
        # is NOT a true barrier on the tunneled axon backend (observed:
        # "878 TFLOP/s" on a 197-TFLOP/s chip before this fix).
        # Chunks are generated and drained BEFORE the clock starts —
        # they are test scaffolding (the trainer stages real
        # transitions), not part of the measured update path.
        nonlocal state, buf
        chunks = [chunk(10 + i) for i in range(n_bursts)]
        for c in chunks:
            # One reduced fetch per chunk that depends on EVERY leaf —
            # draining a single field would let the other arrays'
            # kernels land inside the timed region.
            drain(jax.tree_util.tree_reduce(
                lambda a, leaf: a + jnp.sum(leaf), c, jnp.float32(0.0)
            ))
        t0 = time.perf_counter()
        for c in chunks:
            state, buf, m = burst(state, buf, c, BURST)
        drain(m["loss_q"])
        return n_bursts * BURST / (time.perf_counter() - t0)

    return run


def bench_accelerator(compute_dtype="float32"):
    """Headline number: grad-steps/sec at the reference config through
    the real fused update_burst path."""
    run = _make_bench_fn(OBS_DIM, ACT_DIM, HIDDEN, BATCH,
                         compute_dtype=compute_dtype)
    run(5)  # extra warmup beyond compile
    return run(60)


def bench_sweep(budget_s=240.0):
    """Batch/width scaling: shows where the chip stops being
    latency-bound. Best-effort within a time budget."""
    results = []
    t_start = time.time()
    for batch, hidden, dtype in [
        (512, HIDDEN, "float32"),
        (4096, HIDDEN, "float32"),
        (4096, (1024, 1024), "float32"),
        (4096, (1024, 1024), "bfloat16"),
    ]:
        if time.time() - t_start > budget_s:
            log("sweep budget exhausted; truncating")
            break
        entry = {"batch": batch, "hidden": list(hidden), "dtype": dtype}
        try:
            run = _make_bench_fn(OBS_DIM, ACT_DIM, hidden, batch,
                                 capacity=100_000, compute_dtype=dtype)
            sps = run(2)  # calibration; re-measure properly only if fast
            if BURST * 20 / sps < (budget_s - (time.time() - t_start)):
                sps = run(20)
            entry.update({
                "grad_steps_per_sec": round(sps, 1),
                "examples_per_sec": round(sps * batch, 0),
            })
            log(f"sweep batch={batch} hidden={hidden} {dtype}: {sps:.1f} steps/s")
        except Exception as e:  # noqa: BLE001 — sweep is best-effort
            entry["error"] = repr(e)
        results.append(entry)
    return results


def bench_on_device(budget_s=300.0):
    """Fused on-device env+update loop throughput (envs/ondevice.py):
    the path the host-loop reference cannot express. Best-effort."""
    out = {}
    t_start = time.time()
    try:
        from torch_actor_critic_tpu.sac.ondevice import benchmark_on_device
    except ImportError:
        return {"error": "benchmark_on_device not available"}
    # n_envs=16 matches earlier rounds; the 128-env point shows the
    # fused loop's near-free env scaling (vectorized physics shares the
    # dispatch + update cost); the history-8 point times the fused
    # long-context (causal-transformer) path — shapes the host-loop
    # reference cannot express at all.
    for env_name, n_envs, hist in (
        ("pendulum", 16, 1),
        ("cheetah", 16, 1),
        ("cheetah", 128, 1),
        ("cheetah", 16, 8),
    ):
        key = env_name + ("" if n_envs == 16 else f"@{n_envs}")
        key += "" if hist == 1 else f"_h{hist}"
        if time.time() - t_start > budget_s:
            out[key] = {"error": "budget exhausted"}
            continue
        try:
            out[key] = benchmark_on_device(
                env_name, n_envs=n_envs, history_len=hist
            )
        except Exception as e:  # noqa: BLE001
            out[key] = {"error": repr(e)}
    return out


def bench_attention(budget_s=180.0, t=2048):
    """Flash-attention kernel throughput (the long-context extension's
    hot op): causal fwd and fwd+bwd at a long-context shape, reported
    as achieved TFLOP/s. On TPU this exercises the Pallas kernels both
    directions (auto dispatch); elsewhere the XLA blockwise path."""
    b, h, d = 4, 8, 64
    out = {"shape": [b, h, t, d]}
    t_start = time.time()
    try:
        import jax
        import jax.numpy as jnp

        from torch_actor_critic_tpu.ops.attention import attention

        ks = jax.random.split(jax.random.key(0), 4)
        q, k, v = (
            jax.random.normal(kk, (b, h, t, d), jnp.float32) for kk in ks[:3]
        )
        g = jax.random.normal(ks[3], (b, h, t, d), jnp.float32)

        # Each step folds its output back into q so iteration i+1 has a
        # data dependency on iteration i: an async/pipelining backend
        # (e.g. a tunneled TPU) cannot overlap the timed kernels, which
        # previously produced physically-impossible TFLOP/s readings.
        fwd = jax.jit(
            lambda q, k, v: q * 0.999 + 1e-3 * attention(q, k, v, causal=True)
        )

        def loss_vjp(q, k, v, g):
            _, vjp = jax.vjp(
                lambda q, k, v: attention(q, k, v, causal=True), q, k, v
            )
            # Fold ALL THREE grads into the chained output (tq == tk
            # here, so shapes match) — returning only dq would let XLA
            # dead-code-eliminate the dK/dV backward kernel entirely.
            dq, dk, dv = vjp(g)
            return q * 0.999 + 1e-3 * (dq + dk + dv)

        bwd = jax.jit(loss_vjp)

        # causal: half the score matrix is live -> 0.5 * 4*b*h*t^2*d per
        # fwd; bwd recomputes probs and adds dq/dk/dv matmuls (~2.5x).
        flops_fwd = 0.5 * 4 * b * h * t * t * d
        flops_bwd = 3.5 * flops_fwd  # fwd residual recompute + 2.5x bwd
        from torch_actor_critic_tpu.utils.sync import drain

        def timed(fn, q0, *args):
            drain(fn(q0, *args))  # compile + calibrate
            t0 = time.perf_counter()
            drain(fn(q0, *args))
            once = time.perf_counter() - t0
            n = max(4, min(50, int(5.0 / max(once, 1e-4))))
            r = q0
            t0 = time.perf_counter()
            for _ in range(n):
                r = fn(r, *args)
            drain(r)
            return (time.perf_counter() - t0) / n

        dt = timed(fwd, q, k, v)
        out["fwd_ms"] = round(dt * 1e3, 2)
        out["fwd_tflops"] = round(flops_fwd / dt / 1e12, 2)

        if time.time() - t_start < budget_s:
            dt = timed(bwd, q, k, v, g)
            out["fwd_bwd_ms"] = round(dt * 1e3, 2)
            out["fwd_bwd_tflops"] = round(flops_bwd / dt / 1e12, 2)
        log(f"attention: {out}")
    except Exception as e:  # noqa: BLE001 — best-effort section
        out["error"] = repr(e)
    return out


def bench_host_envs(n_envs=4, budget_s=240.0):
    """Host env-loop throughput with the worker pool on vs off
    (round-1 weak #4: the host loop's env side was unmeasured), through
    the in-process SequentialEnvPool and the native shared-memory
    ParallelEnvPool. Both sampled envs have sub-ms steps (Pendulum ~20us,
    dm cheetah ~0.12ms), so the pool LOSES on them — its lockstep IPC
    round costs ~0.7ms, paying off only when per-step physics exceeds
    ~2ms (composer/pixel envs like the wall-runner, measured at
    ~83ms/step, where 4 workers turn ~330ms lockstep rounds into
    ~90ms). The numbers are reported
    anyway because honest overhead measurement beats a cherry-picked
    win; the `note` key states the crossover."""
    import numpy as np

    from torch_actor_critic_tpu.envs.vec_env import make_env_pool

    out = {
        "note": (
            "both envs are sub-ms/step so the ~0.7ms lockstep IPC round "
            "dominates; the native pool targets >~2ms physics "
            "(composer/pixel envs)"
        )
    }
    t_start = time.time()
    for env_name, env_key, n_steps in (
        ("Pendulum-v1", "pendulum", 400),
        ("dm:cheetah:run", "dm_cheetah", 120),
    ):
        for parallel in (False, True):
            name = f"{env_key}_{'parallel' if parallel else 'sequential'}"
            if time.time() - t_start > budget_s:
                out[name] = {"error": "budget exhausted"}
                continue
            pool = None
            try:
                pool = make_env_pool(
                    env_name, n_envs, base_seed=0, parallel=parallel
                )
                if parallel and type(pool).__name__ != "ParallelEnvPool":
                    out[name] = {"error": "native pool unavailable"}
                    continue
                pool.reset_all([10000 * i for i in range(n_envs)])
                rng = np.random.default_rng(0)
                actions = rng.uniform(
                    -1, 1, (n_steps, n_envs, pool.act_dim)
                ).astype(np.float32)
                for a in actions[:20]:  # warmup
                    pool.step(a)
                t0 = time.perf_counter()
                for a in actions[20:]:
                    pool.step(a)
                dt = time.perf_counter() - t0
                out[name] = {
                    "n_envs": n_envs,
                    "env_steps_per_sec": round((n_steps - 20) * n_envs / dt, 1),
                }
                log(f"host envs {name}: {out[name]}")
            except Exception as e:  # noqa: BLE001 — best-effort section
                out[name] = {"error": repr(e)}
            finally:
                if pool is not None:
                    pool.close()
    return out


def bench_torch_cpu(n_steps=300):
    """Reference-style torch-CPU SAC update, timed per gradient step
    incl. uniform replay sampling — the measured stand-in for the
    unpublished reference baseline. Same shared implementation as the
    return-parity runs (``baselines/torch_sac.py``), so the throughput
    and return baselines can never drift apart."""
    import torch

    from torch_actor_critic_tpu.baselines import build_torch_sac

    _, update = build_torch_sac(OBS_DIM, ACT_DIM, hidden=HIDDEN)

    n = 100_000
    data = {
        "s": torch.randn(n, OBS_DIM),
        "a": torch.tanh(torch.randn(n, ACT_DIM)),
        "r": torch.randn(n),
        "s2": torch.randn(n, OBS_DIM),
        "d": torch.zeros(n),
    }

    def step():
        idx = torch.randint(0, n, (BATCH,))
        update(*(data[k][idx] for k in ("s", "a", "r", "s2", "d")))

    for _ in range(20):  # warmup
        step()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        step()
    return n_steps / (time.perf_counter() - t0)


def peak_flops_for(device_kind):
    env = os.environ.get("TAC_PEAK_FLOPS")
    if env:
        return float(env)
    kind = (device_kind or "").lower()
    for tag, peak in PEAK_FLOPS_BY_KIND:
        if tag in kind:
            return peak
    return None


def _stage_headline():
    """Subprocess entry: headline (parity-config, float32) number."""
    return {"acc_sps": bench_accelerator()}


def _stage_headline_bf16():
    """Subprocess entry: the same burst with compute_dtype=bfloat16
    (MXU-native matmuls, f32 params/optimizer/losses). Its own stage so
    a bf16 hang cannot cost the already-measured f32 headline."""
    return {"acc_sps_bf16": bench_accelerator(compute_dtype="bfloat16")}


_STAGES = {
    "headline": _stage_headline,
    "headline_bf16": _stage_headline_bf16,
    "sweep": lambda: {"sweep": bench_sweep()},
    "on_device": lambda: {"on_device": bench_on_device()},
    # Two sequence lengths: the O(block)-memory kernel's scaling story —
    # 4x the length = 16x the FLOPs at flat VMEM residency.
    "attention": lambda: {
        "attention": bench_attention(t=2048),
        "attention_8k": bench_attention(t=8192),
    },
}


def _run_stage_inprocess(name):
    """Child-process mode: run one stage, print one JSON line, exit 0."""
    # Honor the parent's preflight decision: if it fell back to CPU, a
    # fresh import here would still default to the (dead) accelerator.
    _ensure_platform(os.environ.get("TAC_BENCH_CHILD_PLATFORM"))
    try:
        result = _STAGES[name]()
    except Exception as e:  # noqa: BLE001 — structured over traceback
        result = {"error": repr(e)}
    print(json.dumps(result), flush=True)


def run_stage_subprocess(name, timeout_s, diagnostics, platform=None):
    """Run a bench stage in a subprocess with a hard timeout.

    The round-1 bench died when the TPU backend failed at init; the
    preflight fixed that, but a tunnel that dies MID-bench (observed
    this round: preflight ok, then every TPU op hangs forever) would
    still wedge the parent. A subprocess + timeout turns any hang into
    a structured diagnostic instead of a lost round.
    """
    env = dict(os.environ)
    if platform:
        env["TAC_BENCH_CHILD_PLATFORM"] = platform
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), f"--stage={name}"],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        if proc.returncode == 0 and line:
            return json.loads(line)
        diagnostics.append({
            f"{name}_stage_rc": proc.returncode,
            "stderr_tail": proc.stderr[-500:],
        })
    except subprocess.TimeoutExpired:
        diagnostics.append({f"{name}_stage_error": f"timeout after {timeout_s}s"})
        log(f"stage {name} timed out ({timeout_s}s) — tunnel hang?")
    except Exception as e:  # noqa: BLE001
        diagnostics.append({f"{name}_stage_error": repr(e)})
    return None


def main():
    out = {
        "metric": "sac_grad_steps_per_sec",
        "value": None,
        "unit": "steps/sec",
        "vs_baseline": None,
    }
    diagnostics = []

    # 1. Preflight the accelerator (subprocess; cannot hang the parent).
    info, pf_diags = preflight_backend()
    _ensure_platform(info.get("platform"))
    out["backend"] = info.get("platform")
    out["device_kind"] = info.get("device_kind")
    if pf_diags:
        diagnostics.append({"preflight": pf_diags})

    # 2. Accelerator benchmark FIRST (the number that matters), in a
    # subprocess so a mid-bench tunnel hang cannot wedge the parent.
    acc_sps = None
    if info.get("platform") not in (None, "none"):
        res = run_stage_subprocess(
            "headline", 600, diagnostics, platform=info.get("platform")
        )
        if res and "acc_sps" in res:
            acc_sps = res["acc_sps"]
            out["value"] = round(acc_sps, 1)
            log(f"accelerator: {acc_sps:.1f} grad-steps/s ({info.get('platform')})")
        elif res:
            diagnostics.append({"accelerator_bench_error": res.get("error")})
            log(f"accelerator bench failed: {res.get('error')}")
        res = run_stage_subprocess(
            "headline_bf16", 600, diagnostics, platform=info.get("platform")
        )
        if res and "acc_sps_bf16" in res:
            out["value_bf16"] = round(res["acc_sps_bf16"], 1)
            log(f"accelerator bf16: {out['value_bf16']} grad-steps/s")
        elif res:
            diagnostics.append({"bf16_bench_error": res.get("error")})

    # 3. MFU (analytic FLOPs; negligible-elementwise approximation).
    flops = sac_flops_per_step()
    out["flops_per_step"] = flops
    if acc_sps is not None:
        peak = peak_flops_for(info.get("device_kind"))
        out["achieved_flops_per_sec"] = round(acc_sps * flops, 0)
        if peak:
            out["mfu"] = round(acc_sps * flops / peak, 5)
            out["peak_flops_assumed"] = peak

    # 4./5. Accelerator scaling sections: the batch/width sweep and the
    # fused on-device loop measure chip behavior — on the CPU *fallback*
    # they are meaningless and can take tens of minutes on a 2-thread
    # host, delaying the JSON line past harness timeouts. Skip unless
    # on a real accelerator (TAC_BENCH_FULL=1 overrides for testing).
    full = info.get("platform") != "cpu" or os.environ.get("TAC_BENCH_FULL") == "1"
    if acc_sps is not None and full:
        # One subprocess per section: a hang or overrun in one loses
        # only that section's data, and each timeout covers its own
        # internal budget plus a fresh backend-init + compile.
        for stage, timeout_s in (
            # attention runs two lengths with 180s internal budgets
            # each; its timeout covers both plus init + compiles.
            ("sweep", 420), ("on_device", 540), ("attention", 600)
        ):
            res = run_stage_subprocess(
                stage, timeout_s, diagnostics, platform=info.get("platform")
            )
            if res and "error" in res:
                # Route child failure to diagnostics — a top-level
                # "error" key is reserved for total bench failure.
                diagnostics.append({f"{stage}_stage_error": res.pop("error")})
            if res:
                out.update(res)

    # 5b. Host env-loop throughput (pool on/off) — host-side, cheap,
    # meaningful on any backend.
    try:
        out["host_envs"] = bench_host_envs()
    except Exception as e:  # noqa: BLE001
        diagnostics.append({"host_envs_error": repr(e)})

    # 6. Torch-CPU baseline LAST; pinned fallback if it breaks.
    torch_sps = None
    try:
        torch_sps = bench_torch_cpu()
        out["torch_cpu_steps_per_sec"] = round(torch_sps, 1)
    except Exception as e:  # noqa: BLE001
        diagnostics.append({"torch_baseline_error": repr(e)})
        torch_sps = TORCH_CPU_FALLBACK_SPS
        out["torch_cpu_steps_per_sec"] = torch_sps
        out["torch_baseline_source"] = "pinned_fallback"

    if acc_sps is not None and torch_sps:
        out["vs_baseline"] = round(acc_sps / torch_sps, 2)

    if diagnostics:
        out["diagnostics"] = diagnostics
    if out["value"] is None:
        out["error"] = "no accelerator benchmark completed"

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1].startswith("--stage="):
        _run_stage_inprocess(sys.argv[1].split("=", 1)[1])
        sys.exit(0)
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — last-resort structured line
        print(json.dumps({
            "metric": "sac_grad_steps_per_sec", "value": None,
            "unit": "steps/sec", "vs_baseline": None,
            "error": f"fatal: {e!r}",
        }), flush=True)
    sys.exit(0)
