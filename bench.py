"""Headline benchmark: SAC gradient-steps/sec on one TPU chip.

BASELINE.md: the reference publishes no numbers, so the measured
baseline is a PyTorch-CPU implementation of the same update at the
reference run configuration (alpha=0.2 fixed, gamma=0.99, polyak=0.995,
batch 64, hidden [256,256], lr 3e-4, ``torch.set_num_threads(2)`` as in
ref ``main.py:130``) on HalfCheetah-v3 dimensions (obs 17, act 6).

Prints exactly ONE JSON line on stdout:
    {"metric": "sac_grad_steps_per_sec", "value": N, "unit":
     "steps/sec", "vs_baseline": ratio_vs_torch_cpu, ...}
Extra keys: backend, device_kind, mfu, flops_per_step, sweep (batch/
width MFU scaling), visual (CNN burst at the wall-runner geometry),
on_device (fused env+update loop throughput), host_envs (worker-pool
on/off incl. the wall-runner crossover), telemetry_overhead (Trainer
throughput with telemetry off vs on), obs_overhead (run-wide obs
collector + SLO engine off vs on), diagnostics_overhead (tiered
off/light/full learning-health diagnostics cost), and — on any failure —
"error"/"diagnostics" instead of a silent traceback. Real-chip runs
snapshot themselves into ``runs/tpu/`` and a CPU-fallback run merges
the freshest snapshot back as ``last_known_tpu`` (round-3 hardening:
chip evidence survives a dead tunnel).

Robustness contract (round-2 hardening):
  * The accelerator backend is preflighted in a SUBPROCESS with a
    bounded timeout and retry/backoff — a hung TPU plugin (the round-1
    failure mode: "Unable to initialize backend 'axon'") cannot wedge
    the parent, which falls back to CPU and still emits a line.
  * The TPU benchmark runs BEFORE the torch baseline so an accelerator
    number is recorded even if the baseline path breaks.
  * Every stage is individually guarded; main() never raises and
    always exits 0 with a parseable JSON line.

The TPU number is measured through the real training path — the fused
``update_burst`` (push + 50 sampled gradient steps per dispatch) over
the HBM replay buffer, exactly what the trainer runs.
"""

import functools
import glob
import json
import os
import re
import subprocess
import sys
import time

OBS_DIM, ACT_DIM = 17, 6
BATCH = 64
HIDDEN = (256, 256)
BURST = 50

# Persisted chip evidence (round-3 hardening): every successful
# accelerator bench writes a timestamped artifact here, and a CPU
# fallback run merges the freshest one into its output as
# `last_known_tpu` — a flaky tunnel at capture time can no longer erase
# all real-chip numbers (the round-1/round-2 failure mode, where chip
# results teed to /tmp evaporated with the tunnel).
TPU_EVIDENCE_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "runs", "tpu"
)


def persist_tpu_artifact(out: dict, prefix: str = "bench") -> str | None:
    """Write a timestamped JSON snapshot of a real-accelerator result
    into ``runs/tpu/`` (committed to the repo, unlike /tmp)."""
    # Gate on the backend only: a partial capture (or a future
    # section-only artifact, e.g. attention_*/td3-only) carries real
    # chip sections worth keeping even when the headline stage never
    # ran — load_last_known_tpu() merges those per-key and requires a
    # headline only of the merged result.
    if out.get("backend") in (None, "none", "cpu"):
        return None
    os.makedirs(TPU_EVIDENCE_DIR, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = os.path.join(TPU_EVIDENCE_DIR, f"{prefix}_{stamp}.json")
    record = dict(out)
    record["captured_utc"] = stamp
    record.pop("diagnostics", None)  # transient; keeps artifacts stable
    record.pop("error", None)  # run status, not evidence — a stale
    # error merged under a fresh headline would contradict itself
    record.pop("stage_errors", None)  # run status too, same reason
    metadata = {"backend", "device_kind", "captured_utc", "metric",
                "unit", "notes"}
    if not any(k for k, v in record.items()
               if k not in metadata and v is not None):
        return None  # nothing measured: no headline, no sections
    with open(path, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
    log(f"persisted chip artifact: {path}")
    return path


def load_last_known_tpu() -> dict | None:
    """Chip evidence merged per-key across persisted artifacts, or None.

    Timestamped filenames sort chronologically. The freshest artifact's
    values win key-by-key, but sections it is missing (an incremental
    capture killed mid-run writes only its completed stages) are filled
    from older complete artifacts instead of being lost — the merged
    record's ``artifact`` names the freshest contributor and
    ``merged_from`` lists every contributing file when more than one.
    Corrupt or valueless files are skipped rather than trusted.
    """
    def stamp(path):
        # Order by the timestamp token, not the whole basename — with
        # mixed prefixes (bench_*, future attention_* etc.) the prefix
        # would otherwise dominate and stale files would win the merge.
        m = re.search(r"(\d{8}T\d{6}Z)", os.path.basename(path))
        return m.group(1) if m else os.path.basename(path)

    recs = []
    for p in sorted(glob.glob(os.path.join(TPU_EVIDENCE_DIR, "*.json")),
                    key=stamp):
        try:
            with open(p) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(rec, dict):
            continue
        if rec.get("backend") in (None, "none", "cpu"):
            continue  # CPU/backend-less records never carry chip evidence
        if "metric" not in rec:
            continue  # not a bench-family record (e.g. train_proof_*):
            # different schema; merging its keys would pollute the record
        # No "value" gate here: a section-only artifact (partial
        # capture, attention_*/td3-only record) still contributes its
        # sections to the merge; only the MERGED record must end up
        # with a headline (checked below).
        recs.append((p, rec))
    if not recs:
        return None
    # Only artifacts from the same device as the freshest contributor
    # may fill in missing sections — never publish one chip's numbers
    # under another chip's header.
    freshest_kind = recs[-1][1].get("device_kind")
    merged: dict = {}
    contributors: list[str] = []
    for p, rec in recs:  # oldest -> newest so fresher values overwrite
        if rec.get("device_kind") != freshest_kind:
            continue
        rel = os.path.join("runs", "tpu", os.path.basename(p))
        contributors.append(rel)
        merged.update({k: v for k, v in rec.items() if v is not None})
        if rec.get("value") is not None:
            # "artifact" is the provenance of the HEADLINE number: the
            # freshest record that actually carries one (a fresher
            # section-only artifact may still win other keys above).
            merged["artifact"] = rel
    if len(contributors) > 1:
        merged["merged_from"] = contributors
    # A merged record that still has no headline number (every
    # contributor was a section-only artifact) cannot stand in for a
    # chip benchmark result.
    if merged.get("value") is None:
        return None
    return merged

# Pinned fallback: reference-style torch-CPU SAC measured on this image
# (2 threads, ref main.py:130 config) on 2026-07-29. Used for
# vs_baseline only if the live baseline measurement fails.
TORCH_CPU_FALLBACK_SPS = 143.1

# Peak bf16 FLOP/s per chip generation now lives in ONE place —
# telemetry/costmodel.py (the live roofline layer shares it); bench's
# peak_flops_for() below delegates there, TAC_PEAK_FLOPS override
# included.

# The axon sitecustomize re-registers "axon,cpu" over JAX_PLATFORMS at
# jax import, so a CPU probe/fallback must force the platform via
# jax.config AFTER import but BEFORE backend init (same countermeasure
# as tests/conftest.py).
_PROBE_SRC = """
import json, time, sys
t0 = time.time()
import jax, jax.numpy as jnp
if len(sys.argv) > 1 and sys.argv[1] == "cpu":
    jax.config.update("jax_platforms", "cpu")
devs = jax.devices()
x = jnp.ones((256, 256), jnp.float32)
assert float((x @ x)[0, 0]) == 256.0  # host fetch = true execution barrier
print(json.dumps({
    "platform": devs[0].platform,
    "device_kind": devs[0].device_kind,
    "n_devices": len(devs),
    "init_seconds": round(time.time() - t0, 1),
}))
"""


def _ensure_platform(platform):
    """Force the chosen platform in-process before any backend init."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def preflight_backend():
    """Probe the default (accelerator) backend in a subprocess with
    retry/backoff; on persistent failure probe CPU. Returns
    (info_dict, diagnostics)."""
    diags = []
    attempts = [(90, 10), (120, 20), (150, 0)]
    if os.environ.get("TAC_BENCH_PLATFORM") == "cpu":
        attempts = []  # operator override: skip straight to CPU
    for attempt, (timeout_s, backoff_s) in enumerate(attempts):
        try:
            t0 = time.time()
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if proc.returncode == 0:
                info = json.loads(proc.stdout.strip().splitlines()[-1])
                log(f"preflight ok: {info}")
                return info, diags
            diags.append({
                "attempt": attempt, "rc": proc.returncode,
                "stderr_tail": proc.stderr[-500:],
                "elapsed": round(time.time() - t0, 1),
            })
            log(f"preflight attempt {attempt} rc={proc.returncode}")
        except subprocess.TimeoutExpired:
            diags.append({"attempt": attempt, "error": f"timeout after {timeout_s}s"})
            log(f"preflight attempt {attempt} timed out ({timeout_s}s)")
        except Exception as e:  # noqa: BLE001 — preflight must not raise
            diags.append({"attempt": attempt, "error": repr(e)})
        if backoff_s:
            time.sleep(backoff_s)

    log("accelerator preflight failed; falling back to CPU backend")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC, "cpu"],
            capture_output=True, text=True, timeout=120,
        )
        info = json.loads(proc.stdout.strip().splitlines()[-1])
        log(f"cpu fallback preflight ok: {info}")
    except Exception as e:  # noqa: BLE001
        diags.append({"cpu_fallback_error": repr(e)})
        info = {"platform": "none", "device_kind": "none", "n_devices": 0}
    return info, diags


def sac_flops_per_step(batch=BATCH, hidden=HIDDEN, obs=OBS_DIM, act=ACT_DIM):
    """Analytic FLOPs for one SAC gradient step (critic+policy update),
    dense matmul MACs x2, batch-scaled. Backward through a layer costs
    ~2x its forward; the frozen-critic pass in the policy loss only
    needs input grads (~1x forward extra). Elementwise/Adam/polyak
    terms are negligible and omitted."""
    def mlp_macs(sizes):
        return sum(a * b for a, b in zip(sizes[:-1], sizes[1:]))

    actor = mlp_macs([obs, *hidden]) + 2 * hidden[-1] * act       # trunk + mu/log_std heads
    critic = 2 * mlp_macs([obs + act, *hidden, 1])                # twin Q
    macs = (
        actor          # pi(s') for the backup (no grad)
        + critic       # target twin fwd
        + 3 * critic   # critic twin fwd+bwd
        + 3 * actor    # actor fwd+bwd (policy loss)
        + 2 * critic   # critic fwd + input-only bwd (frozen)
    )
    return 2 * batch * macs


def visual_flops_per_step(feat=168, frame=(64, 64, 3), act_dim=56,
                          batch=32, hidden=(256, 256), cnn_features=1):
    """Analytic FLOPs for one visual SAC gradient step (same fwd/bwd
    weighting as :func:`sac_flops_per_step`), dominated by the four CNN
    towers (actor + twin critic, each with its own conv trunk)."""
    def cnn_macs():
        h, w, c = frame
        macs = 0
        for f, k, s in zip((32, 64, 64), (8, 4, 3), (4, 2, 1)):
            h = (h - k) // s + 1
            w = (w - k) // s + 1
            macs += h * w * f * k * k * c
            c = f
        macs += (h * w * c) * 512 + 512 * cnn_features
        return macs

    def mlp_macs(sizes):
        return sum(a * b for a, b in zip(sizes[:-1], sizes[1:]))

    actor = (
        cnn_macs() + mlp_macs([feat, *hidden])
        + 2 * (hidden[-1] + cnn_features) * act_dim
    )
    critic_mlp = 2 * (mlp_macs([feat + act_dim, *hidden, 1]) + (1 + cnn_features))
    critic = 2 * cnn_macs() + critic_mlp  # twin, each with its own CNN tower
    macs = (
        actor          # pi(s') for the backup (no grad)
        + critic       # target twin fwd
        + 3 * critic   # critic twin fwd+bwd
        + 3 * actor    # actor fwd+bwd (policy loss)
        # frozen-critic policy step: full fwd, but the input-only
        # backward only traverses the MLP branch — the frame input is
        # constant data, so no gradient ever flows through the conv
        # towers (autograd skips them; XLA DCEs them).
        + critic + critic_mlp
    )
    return 2 * batch * macs


def _make_bench_fn(obs_dim, act_dim, hidden, batch, capacity=1_000_000,
                   compute_dtype="float32", burst_unroll=0,
                   algorithm="sac"):
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.buffer import init_replay_buffer, push
    from torch_actor_critic_tpu.core.types import Batch
    from torch_actor_critic_tpu.sac.trainer import build_models, make_learner
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(
        batch_size=batch, hidden_sizes=hidden, compute_dtype=compute_dtype,
        burst_unroll=burst_unroll, algorithm=algorithm,
    )

    class _Spec:  # the flat-obs env surface build_models dispatches on
        obs_spec = jax.ShapeDtypeStruct((obs_dim,), jnp.float32)
        act_limit = 1.0

    _Spec.act_dim = act_dim
    actor, critic = build_models(cfg, _Spec)
    sac = make_learner(cfg, actor, critic, act_dim)
    state = sac.init_state(jax.random.key(0), jnp.zeros((obs_dim,)))
    buf = init_replay_buffer(
        capacity, jax.ShapeDtypeStruct((obs_dim,), jnp.float32), act_dim
    )

    def chunk(key, n=BURST):
        ks = jax.random.split(jax.random.key(key), 5)
        return Batch(
            states=jax.random.normal(ks[0], (n, obs_dim)),
            actions=jnp.tanh(jax.random.normal(ks[1], (n, act_dim))),
            rewards=jax.random.normal(ks[2], (n,)),
            next_states=jax.random.normal(ks[3], (n, obs_dim)),
            done=jnp.zeros((n,)),
        )

    buf = jax.jit(push, donate_argnums=(0,))(buf, chunk(1, 5000))
    burst = jax.jit(sac.update_burst, static_argnums=(3,), donate_argnums=(0, 1))

    from torch_actor_critic_tpu.utils.sync import drain

    state, buf, m = burst(state, buf, chunk(2), BURST)  # compile + warmup
    drain(m["loss_q"])

    def run(n_bursts):
        # Drain with a host fetch (utils/sync.py): each burst chains
        # through the donated (state, buf), so fetching the last burst's
        # loss forces the whole sequence to execute. block_until_ready
        # is NOT a true barrier on the tunneled axon backend (observed:
        # "878 TFLOP/s" on a 197-TFLOP/s chip before this fix).
        # Chunks are generated and drained BEFORE the clock starts —
        # they are test scaffolding (the trainer stages real
        # transitions), not part of the measured update path.
        nonlocal state, buf
        chunks = [chunk(10 + i) for i in range(n_bursts)]
        for c in chunks:
            # One reduced fetch per chunk that depends on EVERY leaf —
            # draining a single field would let the other arrays'
            # kernels land inside the timed region.
            drain(jax.tree_util.tree_reduce(
                lambda a, leaf: a + jnp.sum(leaf), c, jnp.float32(0.0)
            ))
        t0 = time.perf_counter()
        for c in chunks:
            state, buf, m = burst(state, buf, c, BURST)
        drain(m["loss_q"])
        return n_bursts * BURST / (time.perf_counter() - t0)

    return run


def bench_accelerator(compute_dtype="float32"):
    """Headline number: grad-steps/sec at the reference config through
    the real fused update_burst path."""
    run = _make_bench_fn(OBS_DIM, ACT_DIM, HIDDEN, BATCH,
                         compute_dtype=compute_dtype)
    run(5)  # extra warmup beyond compile
    return run(60)


def bench_td3(budget_s=300.0):
    """TD3 fused-burst throughput at the reference config — the second
    algorithm family (extension) through the same update_burst path as
    the SAC headline, for a like-for-like grad-steps/s comparison.

    Calibrates with a 2-burst probe and only buys the full 60-burst
    measurement when it fits the remaining budget (BENCH_r05 killed
    the fixed-65-burst version at the stage timeout, shipping
    nothing); the short number is noisier but always lands."""
    t0 = time.time()
    run = _make_bench_fn(OBS_DIM, ACT_DIM, HIDDEN, BATCH, algorithm="td3")
    sps = run(2)  # calibration
    n = 60
    if BURST * (5 + n) / sps < budget_s - (time.time() - t0):
        run(5)
        sps = run(n)
    return {"grad_steps_per_sec": round(sps, 1), "algorithm": "td3"}


def bench_population(budget_s=420.0):
    """Population scaling at the reference config: N independent
    learners vmapped into one burst (parallel/population.py).

    The round-4 sweep proved the chip does 70% MFU at batch 8192 while
    the product config runs ~1-2% (latency-bound at batch 64); this
    stage measures how much of that idle MXU converts into extra SEEDS:
    aggregate grad-steps/s (all members) vs the N=1 burst. Near-linear
    scaling until the member matmuls fill the MXU is the design claim.
    """
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.core.types import Batch
    from torch_actor_critic_tpu.parallel.population import PopulationLearner
    from torch_actor_critic_tpu.sac.trainer import build_models, make_learner
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.sync import drain

    cfg = SACConfig(batch_size=BATCH, hidden_sizes=HIDDEN)

    class _Spec:
        obs_spec = jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32)
        act_limit = 1.0

    _Spec.act_dim = ACT_DIM
    actor, critic = build_models(cfg, _Spec)
    sac = make_learner(cfg, actor, critic, ACT_DIM)
    capacity = 20_000  # per member; keeps 128 members << HBM

    out = []
    t_start = time.time()
    base_sps = None
    for n_members in (1, 8, 32, 128):
        if time.time() - t_start > budget_s:
            break
        entry = {"members": n_members}
        try:
            pop = PopulationLearner(sac, n_members)
            state = pop.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
            buffer = pop.init_buffer(
                capacity, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32),
                ACT_DIM,
            )

            def chunk(seed, n=BURST):
                ks = jax.random.split(jax.random.key(seed), 5)
                shp = (n_members, n)
                return Batch(
                    states=jax.random.normal(ks[0], shp + (OBS_DIM,)),
                    actions=jnp.tanh(
                        jax.random.normal(ks[1], shp + (ACT_DIM,))
                    ),
                    rewards=jax.random.normal(ks[2], shp),
                    next_states=jax.random.normal(ks[3], shp + (OBS_DIM,)),
                    done=jnp.zeros(shp),
                )

            buffer = pop.push_chunk(buffer, chunk(1, 2000))
            state, buffer, m = pop.update_burst(state, buffer, chunk(2), BURST)
            drain(m["loss_q"])  # compile + warmup
            n_bursts = 40 if n_members <= 32 else 20
            chunks = [chunk(10 + i) for i in range(n_bursts)]
            for c in chunks:
                drain(jax.tree_util.tree_reduce(
                    lambda a, leaf: a + jnp.sum(leaf), c, jnp.float32(0.0)
                ))
            t0 = time.perf_counter()
            for c in chunks:
                state, buffer, m = pop.update_burst(state, buffer, c, BURST)
            drain(m["loss_q"])
            dt = time.perf_counter() - t0
            agg = n_bursts * BURST * n_members / dt
            entry["grad_steps_per_sec_aggregate"] = round(agg, 1)
            if n_members == 1:
                base_sps = agg
            if base_sps is not None:
                # Only ever relative to a MEASURED N=1 point; if that
                # point failed, publishing "scaling_vs_1" against some
                # other N would corrupt the scaling claim.
                entry["scaling_vs_1"] = round(agg / base_sps, 2)
        except Exception as e:  # noqa: BLE001 — per-point best effort
            entry["error"] = repr(e)[:200]
        out.append(entry)
    return out


def bench_population_fused(budget_s=420.0):
    """Population-FUSED scaling: the entire Anakin epoch — envs, replay
    rings, PRNG streams and update bursts — vmapped over N members
    (sac/ondevice.py PopulationOnDeviceLoop), so acting is included,
    not just gradient steps. Reports AGGREGATE env-steps/s and
    grad-steps/s vs N plus an estimated MFU (gradient-burst FLOPs only;
    the pendulum physics is negligible), the conversion rate of the
    measured idle MXU into whole learning curves.
    """
    import jax

    from torch_actor_critic_tpu.envs.ondevice import PendulumJax
    from torch_actor_critic_tpu.sac.ondevice import (
        PopulationOnDeviceLoop,
        _wrap_and_build,
    )
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.sync import drain

    cfg = SACConfig(batch_size=BATCH, hidden_sizes=HIDDEN)
    env_cls, sac = _wrap_and_build(PendulumJax, cfg)
    steps, n_envs = 2 * BURST, 8
    flops = sac_flops_per_step(
        batch=BATCH, hidden=HIDDEN, obs=PendulumJax.obs_dim,
        act=PendulumJax.act_dim,
    )
    try:
        peak = peak_flops_for(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001
        peak = None

    out = []
    t_start = time.time()
    base_sps = None
    for n_members in (1, 8, 32, 128):
        if time.time() - t_start > budget_s:
            break
        entry = {"members": n_members}
        try:
            loop = PopulationOnDeviceLoop(
                sac, env_cls, n_members=n_members, n_envs=n_envs
            )
            ts, buf, es, keys, _ = loop.init(
                jax.random.key(0), buffer_capacity=20_000
            )
            ts, buf, es, keys, _ = loop.epoch(
                ts, buf, es, keys, steps=BURST, update_every=BURST,
                warmup=True,
            )
            # compile the measured shape, then time a fresh dispatch
            ts, buf, es, keys, m = loop.epoch(
                ts, buf, es, keys, steps=steps, update_every=BURST
            )
            drain(m["loss_q"])
            t0 = time.perf_counter()
            ts, buf, es, keys, m = loop.epoch(
                ts, buf, es, keys, steps=steps, update_every=BURST
            )
            drain(m["loss_q"])
            dt = time.perf_counter() - t0
            agg_gs = steps * n_members / dt
            entry["grad_steps_per_sec_aggregate"] = round(agg_gs, 1)
            entry["env_steps_per_sec_aggregate"] = round(
                steps * n_envs * n_members / dt, 1
            )
            if peak:
                entry["est_mfu"] = round(agg_gs * flops / peak, 5)
            if n_members == 1:
                base_sps = agg_gs
            if base_sps is not None:
                entry["scaling_vs_1"] = round(agg_gs / base_sps, 2)
        except Exception as e:  # noqa: BLE001 — per-point best effort
            entry["error"] = repr(e)[:200]
        out.append(entry)
    return out


def bench_sharding(budget_s=420.0):
    """Named-mesh GSPMD scaling (PR 8): the jit-with-sharding dp burst
    at the headline config across mesh shapes dp x fsdp in {1x1, 2x1,
    2x2}, reporting lockstep grad-steps/s, aggregate row throughput
    and estimated PER-DEVICE MFU (each dp shard computes one
    batch-64 gradient per step; fsdp changes layout, not FLOPs), plus
    the population_fused point re-run with the member axis sharded
    P('dp') over every visible device — the two scale-out paths the
    legacy shard_map substrate blocked. On a single-device backend the
    multi-device points record a skip reason (CPU tier-1 proves them
    under the forced-device-count shim; TPU numbers are the artifact).
    """
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.core.types import Batch
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.parallel import (
        DataParallelSAC,
        init_sharded_buffer,
        make_mesh,
        shard_chunk,
    )
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.sync import drain

    n_avail = jax.device_count()
    flops = sac_flops_per_step()
    try:
        peak = peak_flops_for(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001
        peak = None

    def chunk_for(n_dev, per_dev=32):
        ks = jax.random.split(jax.random.key(1), 5)
        shape = (n_dev, per_dev)
        return Batch(
            states=jax.random.normal(ks[0], shape + (OBS_DIM,)),
            actions=jnp.tanh(jax.random.normal(ks[1], shape + (ACT_DIM,))),
            rewards=jax.random.normal(ks[2], shape),
            next_states=jax.random.normal(ks[3], shape + (OBS_DIM,)),
            done=jnp.zeros(shape),
        )

    out = {"device_count": n_avail, "burst": [], }
    t_start = time.time()
    for dp, fsdp in ((1, 1), (2, 1), (2, 2)):
        entry = {"mesh": f"dp{dp}xfsdp{fsdp}"}
        out["burst"].append(entry)
        if dp * fsdp > n_avail:
            entry["skipped"] = f"needs {dp * fsdp} devices, have {n_avail}"
            continue
        if time.time() - t_start > budget_s:
            entry["skipped"] = "budget exhausted"
            continue
        try:
            cfg = SACConfig(hidden_sizes=HIDDEN, batch_size=BATCH)
            sac = SAC(
                cfg,
                Actor(act_dim=ACT_DIM, hidden_sizes=HIDDEN),
                DoubleCritic(hidden_sizes=HIDDEN),
                ACT_DIM,
            )
            learner = DataParallelSAC(sac, make_mesh(dp=dp, fsdp=fsdp))
            state = learner.init_state(
                jax.random.key(0), jnp.zeros((OBS_DIM,))
            )
            buf = init_sharded_buffer(
                100_000, jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32),
                ACT_DIM, learner.mesh,
            )
            chunk = shard_chunk(chunk_for(dp), learner.mesh)
            # compile + warm, then time a fresh dispatch
            state, buf, m = learner.update_burst(state, buf, chunk, BURST)
            drain(m["loss_q"])
            t0 = time.perf_counter()
            state, buf, m = learner.update_burst(state, buf, chunk, BURST)
            drain(m["loss_q"])
            dt = time.perf_counter() - t0
            sps = BURST / dt
            entry["grad_steps_per_sec"] = round(sps, 1)
            # Every dp shard grinds one batch-64 gradient per lockstep
            # step: aggregate row throughput scales with dp.
            entry["rows_per_sec"] = round(sps * BATCH * dp, 1)
            if peak:
                entry["est_mfu_per_device"] = round(sps * flops / peak, 5)
        except Exception as e:  # noqa: BLE001 — per-point best effort
            entry["error"] = repr(e)[:200]
        log(f"sharding {entry}")

    # population_fused with the member axis sharded over dp (the PR 6
    # loop was pinned to one device; this is the unlock).
    pop = {"members": 8, "mesh_dp": n_avail}
    out["population_member_sharded"] = pop
    try:
        from torch_actor_critic_tpu.envs.ondevice import PendulumJax
        from torch_actor_critic_tpu.sac.ondevice import (
            PopulationOnDeviceLoop,
            _wrap_and_build,
        )

        if pop["members"] % n_avail:
            raise ValueError(
                f"population 8 not divisible by {n_avail} devices"
            )
        cfg = SACConfig(batch_size=BATCH, hidden_sizes=HIDDEN)
        env_cls, sac = _wrap_and_build(PendulumJax, cfg)
        p_flops = sac_flops_per_step(
            batch=BATCH, hidden=HIDDEN, obs=PendulumJax.obs_dim,
            act=PendulumJax.act_dim,
        )
        loop = PopulationOnDeviceLoop(
            sac, env_cls, n_members=pop["members"], n_envs=8,
            mesh=make_mesh() if n_avail > 1 else None,
        )
        steps = 2 * BURST
        ts, buf, es, keys, _ = loop.init(
            jax.random.key(0), buffer_capacity=20_000
        )
        ts, buf, es, keys, _ = loop.epoch(
            ts, buf, es, keys, steps=BURST, update_every=BURST, warmup=True
        )
        ts, buf, es, keys, m = loop.epoch(
            ts, buf, es, keys, steps=steps, update_every=BURST
        )
        drain(m["loss_q"])
        t0 = time.perf_counter()
        ts, buf, es, keys, m = loop.epoch(
            ts, buf, es, keys, steps=steps, update_every=BURST
        )
        drain(m["loss_q"])
        dt = time.perf_counter() - t0
        agg = steps * pop["members"] / dt
        pop["grad_steps_per_sec_aggregate"] = round(agg, 1)
        pop["env_steps_per_sec_aggregate"] = round(
            steps * 8 * pop["members"] / dt, 1
        )
        if peak:
            # Per-device MFU: each device grinds members/n_avail curves.
            pop["est_mfu_per_device"] = round(
                agg / max(n_avail, 1) * p_flops / peak, 5
            )
    except Exception as e:  # noqa: BLE001
        pop["error"] = repr(e)[:200]
    log(f"sharding population {pop}")
    return out


def bench_unroll(budget_s=300.0):
    """Burst-scan unroll tuning at the headline config: the per-step
    kernels are launch-bound at batch 64 x [256,256], so unrolling the
    50-step gradient scan trades compile time for loop overhead. The
    product default is auto (burst_unroll=0 -> 5 on TPU, from this
    stage's chip evidence); this reports the full knob curve."""
    out = []
    t_start = time.time()
    for unroll in (1, 2, 5, 10):
        if time.time() - t_start > budget_s:
            break
        entry = {"unroll": unroll}
        try:
            run = _make_bench_fn(OBS_DIM, ACT_DIM, HIDDEN, BATCH,
                                 capacity=100_000, burst_unroll=unroll)
            sps = run(2)  # calibration; buy the long run only if it fits
            if BURST * 45 / sps < budget_s - (time.time() - t_start):
                run(5)
                sps = run(40)
            entry["grad_steps_per_sec"] = round(sps, 1)
        except Exception as e:  # noqa: BLE001 — per-point best effort
            entry["error"] = repr(e)[:200]
        out.append(entry)
        log_point("burst_unroll", entry)
    return out


def bench_sweep(budget_s=600.0):
    """Batch/width MFU scaling: where the chip stops being latency-bound
    and how close the update can get to peak (VERDICT r2 missing #2).

    Spans batch 64->16384 and width 256->4096 in f32 and bf16; each
    point reports achieved FLOP/s and MFU against the device's bf16
    peak (one consistent denominator — f32 entries' MFU understates by
    ~2x on MXU hardware, which is itself the point of the bf16 rows).
    Best-effort within a time budget; truncation is logged, not silent.
    """
    import jax

    kind = jax.devices()[0].device_kind
    peak = peak_flops_for(kind)
    results = []
    t_start = time.time()
    points = [
        # The headline's batch/width/dtype — but at unroll=1 (see the
        # pinned burst_unroll below), so this row is comparable to the
        # other sweep rows, not to the auto-unroll headline value.
        (BATCH, HIDDEN, "float32"),
        (512, HIDDEN, "float32"),
        (4096, HIDDEN, "float32"),
        (8192, HIDDEN, "float32"),
        (4096, (1024, 1024), "float32"),
        (4096, (1024, 1024), "bfloat16"),
        (8192, (2048, 2048), "float32"),
        (8192, (2048, 2048), "bfloat16"),
        # MFU-ceiling probes (bf16 only: the f32 rows above already
        # show the non-MXU penalty): 4x the per-layer FLOPs, then 2x
        # the batch at the best-known width.
        (8192, (4096, 4096), "bfloat16"),
        (16384, (2048, 2048), "bfloat16"),
    ]
    for batch, hidden, dtype in points:
        if time.time() - t_start > budget_s:
            log(f"sweep budget exhausted; dropped points from "
                f"batch={batch} hidden={hidden} {dtype} onward")
            results.append({"truncated_from": [batch, list(hidden), dtype]})
            break
        entry = {"batch": batch, "hidden": list(hidden), "dtype": dtype}
        try:
            # unroll pinned to 1: the sweep measures batch/width
            # scaling, and a 5x-unrolled burst body at width 4096
            # would spend the stage budget on compiles, not points.
            run = _make_bench_fn(OBS_DIM, ACT_DIM, hidden, batch,
                                 capacity=100_000, compute_dtype=dtype,
                                 burst_unroll=1)
            sps = run(2)  # calibration; re-measure properly only if fast
            if BURST * 20 / sps < (budget_s - (time.time() - t_start)):
                sps = run(20)
            flops = sac_flops_per_step(batch=batch, hidden=hidden)
            entry.update({
                "grad_steps_per_sec": round(sps, 1),
                "examples_per_sec": round(sps * batch, 0),
                "achieved_tflops": round(sps * flops / 1e12, 3),
            })
            if peak:
                entry["mfu"] = round(sps * flops / peak, 5)
            log(f"sweep batch={batch} hidden={hidden} {dtype}: "
                f"{sps:.1f} steps/s, {entry['achieved_tflops']} TFLOP/s")
        except Exception as e:  # noqa: BLE001 — sweep is best-effort
            entry["error"] = repr(e)
        results.append(entry)
        log_point("sweep", entry)
    return results


def bench_on_device(budget_s=300.0):
    """Fused on-device env+update loop throughput (envs/ondevice.py):
    the path the host-loop reference cannot express. Best-effort."""
    out = {}
    t_start = time.time()
    try:
        from torch_actor_critic_tpu.sac.ondevice import benchmark_on_device
    except ImportError:
        return {"error": "benchmark_on_device not available"}
    # n_envs=16 matches earlier rounds; the 128-env point shows the
    # fused loop's near-free env scaling (vectorized physics shares the
    # dispatch + update cost); the history-8 point times the fused
    # long-context (causal-transformer) path — shapes the host-loop
    # reference cannot express at all.
    # The pixel point runs the fused loop with ON-CHIP frame
    # rasterization through the visual (CNN) stack — pixel training
    # with zero host involvement.
    for env_name, n_envs, hist in (
        ("pendulum", 16, 1),
        ("cheetah", 16, 1),
        ("cheetah", 128, 1),
        ("cheetah", 16, 8),
        ("pixel", 16, 1),
    ):
        key = env_name + ("" if n_envs == 16 else f"@{n_envs}")
        key += "" if hist == 1 else f"_h{hist}"
        if time.time() - t_start > budget_s:
            out[key] = {"error": "budget exhausted"}
            continue
        try:
            out[key] = benchmark_on_device(
                env_name, n_envs=n_envs, history_len=hist
            )
        except Exception as e:  # noqa: BLE001
            out[key] = {"error": repr(e)}
    return out


def bench_scenarios(budget_s=300.0):
    """Fused-loop throughput per scenarios/ family (multi-agent,
    procedural, multi-task) against the pendulum baseline measured in
    the SAME process/config — the scenario-diversity counterpart of
    `on_device`: how much env-steps/s each workload family costs
    relative to the classic single-agent physics. Best-effort."""
    out = {}
    t_start = time.time()
    try:
        from torch_actor_critic_tpu.sac.ondevice import benchmark_on_device
    except ImportError:
        return {"error": "benchmark_on_device not available"}
    for env_name in ("pendulum", "multiagent", "procedural", "multitask"):
        if time.time() - t_start > budget_s:
            out[env_name] = {"error": "budget exhausted"}
            continue
        try:
            out[env_name] = benchmark_on_device(env_name, n_envs=16)
        except Exception as e:  # noqa: BLE001
            out[env_name] = {"error": repr(e)}
    base = out.get("pendulum", {}).get("env_steps_per_sec")
    if base:
        for env_name, row in out.items():
            if isinstance(row, dict) and row.get("env_steps_per_sec"):
                row["vs_pendulum"] = round(
                    row["env_steps_per_sec"] / base, 3
                )
    return out


def bench_attention(budget_s=180.0, t=2048, block_sweep=False):
    """Flash-attention kernel throughput (the long-context extension's
    hot op): causal fwd and fwd+bwd at a long-context shape, reported
    as achieved TFLOP/s. On TPU this exercises the Pallas kernels both
    directions (auto dispatch); elsewhere the XLA blockwise path."""
    b, h, d = 4, 8, 64
    out = {"shape": [b, h, t, d]}
    t_start = time.time()
    try:
        import jax
        import jax.numpy as jnp

        from torch_actor_critic_tpu.ops.attention import attention

        ks = jax.random.split(jax.random.key(0), 4)
        q, k, v = (
            jax.random.normal(kk, (b, h, t, d), jnp.float32) for kk in ks[:3]
        )
        g = jax.random.normal(ks[3], (b, h, t, d), jnp.float32)

        # Each step folds its output back into q so iteration i+1 has a
        # data dependency on iteration i: an async/pipelining backend
        # (e.g. a tunneled TPU) cannot overlap the timed kernels, which
        # previously produced physically-impossible TFLOP/s readings.
        fwd = jax.jit(
            lambda q, k, v: q * 0.999 + 1e-3 * attention(q, k, v, causal=True)
        )

        def loss_vjp_blocks(q, k, v, g, attn=None):
            _, vjp = jax.vjp(
                attn or (lambda q, k, v: attention(q, k, v, causal=True)),
                q, k, v,
            )
            # Fold ALL THREE grads into the chained output (tq == tk
            # here, so shapes match) — returning only dq would let XLA
            # dead-code-eliminate the dK/dV backward kernel entirely.
            dq, dk, dv = vjp(g)
            return q * 0.999 + 1e-3 * (dq + dk + dv)

        bwd = jax.jit(loss_vjp_blocks)

        # causal: half the score matrix is live -> 0.5 * 4*b*h*t^2*d per
        # fwd; bwd recomputes probs and adds dq/dk/dv matmuls (~2.5x).
        flops_fwd = 0.5 * 4 * b * h * t * t * d
        flops_bwd = 3.5 * flops_fwd  # fwd residual recompute + 2.5x bwd
        from torch_actor_critic_tpu.utils.sync import drain

        def timed(fn, q0, *args):
            drain(fn(q0, *args))  # compile + calibrate
            t0 = time.perf_counter()
            drain(fn(q0, *args))
            once = time.perf_counter() - t0
            n = max(4, min(50, int(5.0 / max(once, 1e-4))))
            r = q0
            t0 = time.perf_counter()
            for _ in range(n):
                r = fn(r, *args)
            drain(r)
            return (time.perf_counter() - t0) / n

        dt = timed(fwd, q, k, v)
        out["fwd_ms"] = round(dt * 1e3, 2)
        out["fwd_tflops"] = round(flops_fwd / dt / 1e12, 2)

        if time.time() - t_start < budget_s:
            dt = timed(bwd, q, k, v, g)
            out["fwd_bwd_ms"] = round(dt * 1e3, 2)
            out["fwd_bwd_tflops"] = round(flops_bwd / dt / 1e12, 2)

        # bf16 operands: the kernels keep sub-f32 dtypes on the MXU
        # (f32 accumulation) — the dtype the sequence stack trains in
        # under compute_dtype=bfloat16, and the fast systolic path.
        if time.time() - t_start < budget_s:
            qb, kb, vb, gb = (
                x.astype(jnp.bfloat16) for x in (q, k, v, g)
            )
            dt = timed(fwd, qb, kb, vb)
            out["fwd_ms_bf16"] = round(dt * 1e3, 2)
            out["fwd_tflops_bf16"] = round(flops_fwd / dt / 1e12, 2)
        if time.time() - t_start < budget_s:
            dt = timed(bwd, qb, kb, vb, gb)
            out["fwd_bwd_ms_bf16"] = round(dt * 1e3, 2)
            out["fwd_bwd_tflops_bf16"] = round(flops_bwd / dt / 1e12, 2)

        # Pallas block-size tuning (TPU only — the XLA path ignores
        # block_q): fwd+bwd bf16 at a few (block_q, block_k) tilings.
        # The un-suffixed rows above run the product default (auto
        # blocks, 512-capped — chosen FROM this sweep's chip data).
        # Opt-in per call: each point pays a fresh Pallas fwd+bwd
        # compile, so the caller must budget for it.
        if block_sweep and jax.default_backend() == "tpu":
            from torch_actor_critic_tpu.ops.attention import flash_attention

            # (block_q, block_k, pad_lanes): 128 = the zero-padded
            # native lane layout; 64 keeps a d=64 head at true width
            # (half the q/k/v/o HBM traffic — the MXU is 50%-bounded
            # at d=64 either way, see SCALING.md's attention roofline).
            # Decision-relevant points first (the stage budget may
            # truncate the tail): the incumbent (512,512,128) and the
            # round-4 candidates, then the historical small blocks.
            sweep = []
            for bq, bk, lanes in (
                (512, 512, 128), (512, 512, 64),
                (1024, 1024, 128), (1024, 1024, 64),
                (512, 1024, 128), (256, 512, 128),
                (256, 256, 128), (128, 256, 128),
            ):
                if time.time() - t_start > budget_s:
                    break
                try:
                    f = jax.jit(functools.partial(
                        loss_vjp_blocks,
                        attn=functools.partial(
                            flash_attention, causal=True, block_q=bq,
                            block_k=bk, pad_lanes=lanes,
                        ),
                    ))
                    dt = timed(f, qb, kb, vb, gb)
                    sweep.append({
                        "block_q": bq, "block_k": bk, "pad_lanes": lanes,
                        "fwd_bwd_ms": round(dt * 1e3, 2),
                        "fwd_bwd_tflops": round(flops_bwd / dt / 1e12, 2),
                    })
                except Exception as e:  # noqa: BLE001 — per-point
                    sweep.append({"block_q": bq, "block_k": bk,
                                  "pad_lanes": lanes,
                                  "error": repr(e)[:200]})
            if sweep:
                out["block_sweep"] = sweep
                best = max(
                    (s for s in sweep if "fwd_bwd_tflops" in s),
                    key=lambda s: s["fwd_bwd_tflops"],
                    default=None,
                )
                if best and "fwd_bwd_tflops_bf16" in out:
                    out["best_blocks"] = [best["block_q"], best["block_k"]]
                    out["best_pad_lanes"] = best.get("pad_lanes", 128)
                    out["best_blocks_tflops"] = max(
                        best["fwd_bwd_tflops"], out["fwd_bwd_tflops_bf16"]
                    )
        # Roofline context for the numbers above (SCALING.md, attention
        # section): at d=64 both kernel matmuls run a 64-wide
        # contraction/output on the 128x128 MXU, so the achievable
        # ceiling is <=50% of nominal peak regardless of software.
        out["achievable_peak_frac_d64"] = 0.5
        log(f"attention: {out}")
    except Exception as e:  # noqa: BLE001 — best-effort section
        out["error"] = repr(e)
    return out


def bench_visual(budget_s=300.0, burst=25):
    """Visual (CNN) update_burst throughput at the real wall-runner
    geometry — BASELINE config 5's perf half (VERDICT r2 missing #4):
    168 proprioceptive features + a 64x64x3 uint8 egocentric frame,
    act_dim 56 (ref ``networks/convolutional.py:54-183``,
    ``environments/wall_runner.py``). Reports grad-steps/sec plus the
    HBM footprint of the uint8 replay shard the throughput rides on.
    Runs on any backend (chip when the tunnel is up, CPU otherwise —
    the backend is recorded alongside)."""
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.buffer import init_visual_replay_buffer, push
    from torch_actor_critic_tpu.buffer.replay import estimate_buffer_bytes
    from torch_actor_critic_tpu.core.types import Batch, MultiObservation
    from torch_actor_critic_tpu.envs.wall_runner import (
        ACT_DIM, FEATURE_DIM, FRAME_SHAPE,
    )
    from torch_actor_critic_tpu.models import VisualActor, VisualDoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.sync import drain

    feat, frame, act_dim, batch = FEATURE_DIM, FRAME_SHAPE, ACT_DIM, 32
    capacity = 20_000
    out = {
        "geometry": {
            "features": feat, "frame": list(frame), "act_dim": act_dim,
            "batch": batch, "burst": burst,
        },
        "backend": jax.default_backend(),
        "buffer_capacity": capacity,
        "buffer_hbm_bytes": estimate_buffer_bytes(
            capacity,
            MultiObservation(
                features=jax.ShapeDtypeStruct((feat,), jnp.float32),
                frame=jax.ShapeDtypeStruct(frame, jnp.uint8),
            ),
            act_dim,
        ),
    }
    t_start = time.time()

    def obs(key_f, key_p, n):
        return MultiObservation(
            features=jax.random.normal(key_f, (n, feat)),
            frame=jax.random.randint(key_p, (n, *frame), 0, 256, jnp.uint8),
        )

    def chunk(seed, n=burst):
        ks = jax.random.split(jax.random.key(seed), 6)
        return Batch(
            states=obs(ks[0], ks[1], n),
            actions=jnp.tanh(jax.random.normal(ks[2], (n, act_dim))),
            rewards=jax.random.normal(ks[3], (n,)),
            next_states=obs(ks[4], ks[5], n),
            done=jnp.zeros((n,)),
        )

    def measure(bsz, compute_dtype, pipeline="reference"):
        """Build the full visual stack at one (batch, dtype, pixel
        pipeline) point and time the fused burst; returns calibrated
        grad-steps/sec."""
        cfg = SACConfig(batch_size=bsz, compute_dtype=compute_dtype,
                        pixel_pipeline=pipeline)
        dt_ = cfg.model_dtype
        sac = SAC(cfg, VisualActor(act_dim=act_dim, dtype=dt_),
                  VisualDoubleCritic(dtype=dt_), act_dim)
        state = sac.init_state(
            jax.random.key(0),
            MultiObservation(
                features=jnp.zeros((feat,)), frame=jnp.zeros(frame, jnp.uint8)
            ),
        )
        buf = init_visual_replay_buffer(capacity, feat, frame, act_dim)
        buf = jax.jit(push, donate_argnums=(0,))(buf, chunk(2, 2000))
        burst_fn = jax.jit(
            sac.update_burst, static_argnums=(3,), donate_argnums=(0, 1)
        )
        state, buf, m = burst_fn(state, buf, chunk(3), burst)  # compile
        drain(m["loss_q"])

        def run(n_bursts):
            nonlocal state, buf
            chunks = [chunk(10 + i) for i in range(n_bursts)]
            for c in chunks:
                drain(jax.tree_util.tree_reduce(
                    lambda a, leaf: a + jnp.sum(leaf, dtype=jnp.float32),
                    c, jnp.float32(0.0),
                ))
            t0 = time.perf_counter()
            for c in chunks:
                state, buf, m = burst_fn(state, buf, c, burst)
            drain(m["loss_q"])
            return n_bursts * burst / (time.perf_counter() - t0)

        sps = run(2)  # calibration
        if burst * 20 / sps < (budget_s - (time.time() - t_start)):
            sps = run(20)
        return sps

    sps = measure(batch, "float32")
    out["grad_steps_per_sec"] = round(sps, 1)
    out["examples_per_sec"] = round(sps * batch, 0)
    out.update(mfu_metrics(
        sps, jax.devices()[0].device_kind,
        flops=visual_flops_per_step(feat, frame, act_dim, batch),
    ))
    log_point("visual_points", dict(out.get("geometry", {}),
                                    dtype="float32", pipeline="reference",
                                    grad_steps_per_sec=out["grad_steps_per_sec"]))

    # The mixed-precision + fused-pixel-pipeline training path (the
    # visual-MFU tentpole, docs/SCALING.md "Mixed precision & the
    # pixel pipeline"): the same stack at compute_dtype=bfloat16, then
    # bf16 with pixel_pipeline="fused" (replay-gather -> uint8 decode
    # -> cast fused at sample time — no f32 frame batch in HBM).
    # Measured on any backend so the before/after artifact exists even
    # on the CPU fallback; the 0.2+ MFU target is a chip number.
    for variant, dtype_, pipeline in (
        ("bf16", "bfloat16", "reference"),
        ("bf16_fused", "bfloat16", "fused"),
    ):
        if time.time() - t_start > budget_s:
            out[variant] = {"error": "budget exhausted"}
            continue
        try:
            sps_v = measure(batch, dtype_, pipeline)
            out[variant] = {
                "batch": batch, "dtype": dtype_, "pipeline": pipeline,
                "grad_steps_per_sec": round(sps_v, 1),
                "examples_per_sec": round(sps_v * batch, 0),
                **mfu_metrics(
                    sps_v, jax.devices()[0].device_kind,
                    flops=visual_flops_per_step(feat, frame, act_dim, batch),
                ),
            }
            log_point("visual_points", dict(
                dtype=dtype_, pipeline=pipeline,
                grad_steps_per_sec=out[variant]["grad_steps_per_sec"],
            ))
        except Exception as e:  # noqa: BLE001 — extra point, best effort
            out[variant] = {"error": repr(e)[:200]}

    # Large-batch bf16+fused point (TPU only — a CPU fallback would
    # burn the whole budget): where the conv towers leave the
    # latency-bound regime; MFU against the CNN-aware analytic FLOPs.
    # This is the 0.18-MFU probe made the real training path.
    if jax.default_backend() == "tpu" and time.time() - t_start < budget_s:
        try:
            big = 512
            sps_big = measure(big, "bfloat16", "fused")
            out["large_batch"] = {
                "batch": big, "dtype": "bfloat16", "pipeline": "fused",
                "grad_steps_per_sec": round(sps_big, 1),
                "examples_per_sec": round(sps_big * big, 0),
                **mfu_metrics(
                    sps_big, jax.devices()[0].device_kind,
                    flops=visual_flops_per_step(feat, frame, act_dim, big),
                ),
            }
        except Exception as e:  # noqa: BLE001 — extra point, best effort
            out["large_batch"] = {"error": repr(e)[:200]}

    # Reference-style torch-CPU visual baseline at the same geometry
    # (BASELINE config 5's ratio; the flat headline has its own).
    try:
        out.update(bench_torch_visual(
            feat, frame, act_dim, batch,
            budget_s=budget_s - (time.time() - t_start) - 30,
        ))
        if out.get("torch_cpu_steps_per_sec"):
            out["vs_baseline"] = round(sps / out["torch_cpu_steps_per_sec"], 2)
            if out["backend"] == "cpu" and out["vs_baseline"] < 1:
                out["cpu_note"] = (
                    "XLA:CPU's NHWC convs lag torch's MKL-DNN NCHW path; "
                    "the NHWC/uint8 layout is chosen for TPU (native conv "
                    "layout, 4x smaller replay) — compare the chip-backed "
                    "number, not this fallback"
                )
    except Exception as e:  # noqa: BLE001 — ratio is best-effort
        out["torch_baseline_error"] = repr(e)
    log(f"visual burst: {out['grad_steps_per_sec']} grad-steps/s "
        f"({out['backend']}), vs torch {out.get('vs_baseline')}")
    return out


def bench_torch_visual(feat, frame, act_dim, batch, n_steps=15, budget_s=180.0):
    """Torch-CPU visual SAC gradient-step throughput at the wall-runner
    geometry (``baselines/torch_sac.py:build_torch_visual_sac`` — the
    same shared-baseline discipline as the flat headline). NCHW float
    frames, as the reference stores them. Batches are pre-generated
    OUTSIDE the clock, mirroring the JAX side's pre-drained chunks, so
    vs_baseline compares pure update cost on both sides."""
    if budget_s < 45:
        # A warmup + one timed step can take tens of seconds on a slow
        # host; starting with no budget would overrun the stage's hard
        # timeout and lose the already-measured JAX section with it.
        return {"torch_baseline_skipped": f"budget exhausted ({budget_s:.0f}s)"}

    import torch

    from torch_actor_critic_tpu.baselines import build_torch_visual_sac

    _, update = build_torch_visual_sac(feat, frame[:2], frame[2], act_dim)
    g = torch.Generator().manual_seed(0)

    def data():
        return (
            torch.randn(batch, feat, generator=g),
            torch.rand(batch, frame[2], *frame[:2], generator=g) * 255.0,
            torch.tanh(torch.randn(batch, act_dim, generator=g)),
            torch.randn(batch, generator=g),
            torch.randn(batch, feat, generator=g),
            torch.rand(batch, frame[2], *frame[:2], generator=g) * 255.0,
            torch.zeros(batch),
        )

    t_start = time.time()
    batches = [data() for _ in range(n_steps)]
    update(*data())  # warmup
    t0 = time.perf_counter()
    done = 0
    for b in batches:
        update(*b)
        done += 1
        if time.time() - t_start > budget_s:
            break
    sps = done / (time.perf_counter() - t0)
    return {"torch_cpu_steps_per_sec": round(sps, 2)}


def _measure_pool(env_name, n_envs, n_steps, parallel, warmup=None):
    """Steps/sec of one env pool configuration, plus its build time.

    Warmup steps are excluded from the clock; the pool is closed even on
    failure so worker processes never leak into later sections.
    """
    import numpy as np

    from torch_actor_critic_tpu.envs.vec_env import make_env_pool

    warmup = max(2, n_steps // 10) if warmup is None else warmup
    pool = None
    try:
        t_build = time.perf_counter()
        pool = make_env_pool(env_name, n_envs, base_seed=0, parallel=parallel)
        if parallel and type(pool).__name__ != "ParallelEnvPool":
            return {"error": "native pool unavailable"}
        pool.reset_all([10000 * i for i in range(n_envs)])
        build_s = time.perf_counter() - t_build
        rng = np.random.default_rng(0)
        actions = rng.uniform(
            -1, 1, (n_steps + warmup, n_envs, pool.act_dim)
        ).astype(np.float32)
        for a in actions[:warmup]:
            pool.step(a)
        t0 = time.perf_counter()
        for a in actions[warmup:]:
            pool.step(a)
        dt = time.perf_counter() - t0
        return {
            "n_envs": n_envs,
            "env_steps_per_sec": round(n_steps * n_envs / dt, 1),
            "ms_per_lockstep_round": round(dt / n_steps * 1e3, 2),
            "build_s": round(build_s, 1),
        }
    except Exception as e:  # noqa: BLE001 — best-effort section
        return {"error": repr(e)}
    finally:
        if pool is not None:
            pool.close()


def bench_host_envs(n_envs=4, budget_s=600.0):
    """Host env-loop throughput: native shared-memory ParallelEnvPool vs
    in-process SequentialEnvPool across the step-cost spectrum
    (VERDICT r2 missing #3 / weak #6 — the pool's target regime was
    unmeasured).

    Three regimes: sub-ms envs (Pendulum ~20us, dm cheetah ~0.12ms)
    where the ~0.7ms lockstep IPC round makes the pool LOSE — reported
    anyway, honest overhead beats a cherry-picked win; an n_envs
    scaling curve on dm cheetah showing how the loss evolves with
    worker count; and the pool's target, the composer wall-runner (ref
    ``environments/wall_runner.py:17-62``, ~175ms of physics per step),
    where workers can overlap physics — given cores to run on. The
    measured sandbox is a 1-core host, where workers physically
    serialize and the best possible outcome is parity (IPC amortized);
    ``host_cores`` is recorded and ``crossover_note`` states the
    per-core-count conclusion instead of pretending the topology away."""
    n_cores = os.cpu_count() or 1
    out = {
        "host_cores": n_cores,
        "note": (
            "pendulum/dm_cheetah are sub-ms/step so the ~0.7ms lockstep "
            "IPC round dominates and sequential wins; the wall-runner "
            "row is the pool's target regime (>~2ms physics/step). The "
            "pool needs >=2 host cores to overlap physics at all — "
            "worker processes serialize on a 1-core host."
        ),
    }
    t_start = time.time()

    def left():
        return budget_s - (time.time() - t_start)

    for env_name, env_key, n_steps in (
        ("Pendulum-v1", "pendulum", 380),
        ("dm:cheetah:run", "dm_cheetah", 100),
    ):
        for parallel in (False, True):
            name = f"{env_key}_{'parallel' if parallel else 'sequential'}"
            if left() <= 0:
                out[name] = {"error": "budget exhausted"}
                continue
            out[name] = _measure_pool(env_name, n_envs, n_steps, parallel)
            log(f"host envs {name}: {out[name]}")

    # n_envs scaling on the cheap env: per-round IPC cost vs fan-out.
    scaling = {"env": "dm:cheetah:run", "points": []}
    for n in (1, 2, 4, 8):
        if left() < 30:
            scaling["points"].append({"n_envs": n, "error": "budget exhausted"})
            continue
        scaling["points"].append({
            "n_envs": n,
            "sequential": _measure_pool("dm:cheetah:run", n, 80, False),
            "parallel": _measure_pool("dm:cheetah:run", n, 80, True),
        })
    out["scaling"] = scaling

    # The expensive-env point the pool exists for. Construction builds a
    # CMU-humanoid composer scene (~1 min per env, workers build
    # concurrently), so steps are few and the budget guard is generous.
    wall = {}
    for parallel in (True, False):
        name = "parallel" if parallel else "sequential"
        if left() < (60 if parallel else 100):
            wall[name] = {"error": "budget exhausted"}
            continue
        wall[name] = _measure_pool(
            "DeepMindWallRunner-v0", n_envs, 24, parallel, warmup=4
        )
        log(f"host envs wall_runner_{name}: {wall[name]}")
    out["wall_runner"] = wall

    seq = wall.get("sequential", {}).get("env_steps_per_sec")
    par = wall.get("parallel", {}).get("env_steps_per_sec")
    if seq and par:
        if n_cores == 1:
            # Explicit negative result (VERDICT r2 item 3): process
            # parallelism cannot beat sequential stepping without a
            # second core. On the heavy env the IPC round is fully
            # amortized (ratio ~1.0); on sub-ms envs it dominates. The
            # pool stays OFF by default (config.parallel_envs=False).
            out["crossover_note"] = (
                f"1-core host: wall-runner ({n_envs} envs) parallel {par} "
                f"vs sequential {seq} env-steps/s ({par / seq:.2f}x) — "
                "workers serialize physics, so parity-within-noise is the "
                "ceiling here (measured 0.94x-1.24x across runs); the "
                "pool targets >=2-core hosts with >~2ms/step physics, "
                "and is off by default"
            )
        else:
            out["crossover_note"] = (
                f"wall-runner ({n_envs} envs, {n_cores} cores): parallel "
                f"{par} vs sequential {seq} env-steps/s ({par / seq:.2f}x); "
                "the pool pays off once per-step physics exceeds the ~2ms "
                "IPC round, loses below it (see sub-ms rows)"
            )
    return out


def bench_serving(budget_s=180.0, n_threads=16, requests_per_thread=150):
    """Policy-serving throughput through the real serve/ stack: an
    in-process :class:`PolicyClient` fan-out of concurrent single-obs
    requests through the micro-batcher and the bucketed jitted forward
    (exactly the path the HTTP frontend parks on). Reports
    requests/sec, latency percentiles and mean batch occupancy — the
    numbers docs/SERVING.md's tuning section is about."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.models import Actor
    from torch_actor_critic_tpu.serve import (
        MicroBatcher,
        ModelRegistry,
        PolicyClient,
    )

    t_start = time.time()
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=HIDDEN)
    params = actor.init(
        jax.random.key(0), jnp.zeros((OBS_DIM,)), jax.random.key(1)
    )
    obs_spec = jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32)
    registry = ModelRegistry()
    max_batch = 64
    registry.register(
        "default", actor, obs_spec, params=params, max_batch=max_batch,
    )  # warmup compiles every bucket before the clock starts
    out = {
        "obs_dim": OBS_DIM, "act_dim": ACT_DIM,
        "hidden": list(HIDDEN), "max_batch": max_batch,
        "n_client_threads": n_threads,
        "backend": jax.default_backend(),
    }
    rng = np.random.default_rng(0)
    all_obs = rng.standard_normal((n_threads, OBS_DIM)).astype(np.float32)
    errors = []

    with MicroBatcher(registry, max_batch=max_batch, max_wait_ms=2.0) as mb:
        client = PolicyClient(registry, mb)

        def worker(i):
            try:
                for _ in range(requests_per_thread):
                    client.act(all_obs[i], deterministic=True)
                    if time.time() - t_start > budget_s:
                        return
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                errors.append(repr(e)[:200])

        # a short rinse so the timed window starts steady-state
        client.act(all_obs[0], deterministic=True)
        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(n_threads)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=budget_s + 60)
        elapsed = time.perf_counter() - t0
        snap = mb.metrics.snapshot()

    done = snap["responses_total"] - 1  # minus the rinse request
    out.update({
        "requests": done,
        "requests_per_sec": round(done / elapsed, 1),
        "p50_ms": snap.get("p50_ms"),
        "p95_ms": snap.get("p95_ms"),
        "p99_ms": snap.get("p99_ms"),
        "mean_batch_occupancy": snap.get("mean_batch_occupancy"),
        "mean_rows_per_batch": snap.get("mean_rows_per_batch"),
        "batches_total": snap["batches_total"],
    })
    if errors:
        out["errors"] = errors[:5]
    log(f"serving: {out['requests_per_sec']} req/s, "
        f"p50 {out['p50_ms']}ms p99 {out['p99_ms']}ms, "
        f"occupancy {out['mean_batch_occupancy']}")
    return out


def bench_overload(budget_s=180.0, capacity=64):
    """Overload behavior at 2x capacity (docs/SERVING.md "Overload &
    degradation"): calibrate the stack's saturated service rate, then
    offer twice that for a fixed window with a bounded queue and
    per-request deadlines, and record what admission control did —
    goodput (accepted AND answered per second), shed rate and
    breakdown, queue-bound compliance, and tail latency under
    overload. The acceptance story: goodput should hold near the
    calibrated service rate while the excess is rejected with
    structured 429/503s, instead of every request getting slower
    forever (the unbounded-queue failure mode this layer replaced)."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.models import Actor
    from torch_actor_critic_tpu.resilience.faultinject import flood
    from torch_actor_critic_tpu.serve import (
        MicroBatcher,
        ModelRegistry,
        ShedError,
    )

    t_start = time.time()
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=HIDDEN)
    params = actor.init(
        jax.random.key(0), jnp.zeros((OBS_DIM,)), jax.random.key(1)
    )
    obs_spec = jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32)
    registry = ModelRegistry()
    max_batch = 64
    registry.register(
        "default", actor, obs_spec, params=params, max_batch=max_batch,
    )
    obs = np.ones((OBS_DIM,), np.float32)
    out = {
        "capacity": capacity, "max_batch": max_batch,
        "backend": jax.default_backend(),
    }

    with MicroBatcher(
        registry, max_batch=max_batch, max_wait_ms=2.0, capacity=capacity
    ) as mb:
        # Calibration: closed-loop saturation from a small herd gives
        # the achievable service rate (requests/s) for 1-row requests.
        cal_stop = threading.Event()
        cal_done = [0] * 8

        def cal_worker(i):
            while not cal_stop.is_set():
                mb.act(obs, timeout=30.0)
                cal_done[i] += 1

        cal_threads = [
            threading.Thread(target=cal_worker, args=(i,))
            for i in range(len(cal_done))
        ]
        t0 = time.perf_counter()
        for th in cal_threads:
            th.start()
        cal_window = min(10.0, budget_s / 6)
        time.sleep(cal_window)
        cal_stop.set()
        for th in cal_threads:
            th.join(timeout=30.0)
        service_rate = sum(cal_done) / (time.perf_counter() - t0)
        out["service_rate_rps"] = round(service_rate, 1)

        # Overload window: offer 2x the calibrated rate, paced
        # open-loop across a thread herd, each request carrying a
        # deadline so the infeasible/expired paths are exercised too.
        offered_rate = 2.0 * max(service_rate, 1.0)
        n_threads = 16
        window_s = min(20.0, max(5.0, budget_s - (time.time() - t_start) - 30))
        interval = n_threads / offered_rate
        futures, sheds = [], []
        flood_lock = threading.Lock()
        depth_max = [0]
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                depth_max[0] = max(depth_max[0], mb.queue_depth())
                time.sleep(0.002)

        def offer_worker(i):
            t_next = time.perf_counter() + (i / n_threads) * interval
            t_end = time.perf_counter() + window_s
            local_f, local_s = [], []
            while time.perf_counter() < t_end:
                delay = t_next - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                t_next += interval
                f, s = flood(mb.submit, obs, 1, deadline_s=0.5)
                local_f += f
                local_s += s
            with flood_lock:
                futures.extend(local_f)
                sheds.extend(local_s)

        smp = threading.Thread(target=sampler, daemon=True)
        smp.start()
        workers = [
            threading.Thread(target=offer_worker, args=(i,))
            for i in range(n_threads)
        ]
        t0 = time.perf_counter()
        for th in workers:
            th.start()
        for th in workers:
            th.join(timeout=window_s + 60)
        answered, expired = 0, 0
        for f in futures:
            try:
                f.result(timeout=60)
                answered += 1
            except ShedError:
                expired += 1
        elapsed = time.perf_counter() - t0
        stop.set()
        snap = mb.metrics.snapshot()

    offered = len(futures) + len(sheds)
    out.update({
        "offered_rate_rps": round(offered / elapsed, 1),
        "target_offered_rate_rps": round(offered_rate, 1),
        "goodput_rps": round(answered / elapsed, 1),
        "answered": answered,
        "shed_submit": len(sheds),
        "shed_expired": expired,
        "shed_fraction": round((len(sheds) + expired) / max(offered, 1), 4),
        "shed_by_reason": snap["shed_by_reason"],
        "max_queue_depth": depth_max[0],
        "queue_bound_held": depth_max[0] <= capacity,
        "p50_ms": snap.get("p50_ms"),
        "p99_ms": snap.get("p99_ms"),
    })
    registry.close()
    log(f"overload: offered {out['offered_rate_rps']} rps (2x capacity "
        f"{out['service_rate_rps']}), goodput {out['goodput_rps']} rps, "
        f"shed {out['shed_fraction'] * 100:.1f}%, max queue depth "
        f"{out['max_queue_depth']}/{capacity}")
    return out


def bench_fleet(budget_s=300.0, service_ms=8.0, replica_counts=(1, 2, 4)):
    """Fleet serving scale-out (docs/SERVING.md "Fleet"): aggregate
    goodput + tail latency vs engine-replica count through the REAL
    EngineFleet (per-device engines, least-loaded dispatch, shared
    admission), plus continuous-vs-group batching p50 at low offered
    load.

    The engine forward is pinned to a fixed simulated service time
    (``service_ms`` sleep around the real jitted forward): on the
    1-core CPU bench host real forwards cannot scale past one core, so
    the stage measures what actually matters and transfers to real
    hardware — whether the fleet's dispatch plane OVERLAPS N engines'
    service times (on a TPU host each replica's forward runs on its
    own chip; the host-side dispatch path benched here is identical).
    Scaling ~N in ``scaling_vs_1`` means the dispatcher, shared
    admission and per-replica queues add no serialization."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.models import Actor
    from torch_actor_critic_tpu.serve import (
        EngineFleet,
        MicroBatcher,
        ModelRegistry,
        ServeMetrics,
    )

    t_start = time.time()
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=HIDDEN)
    params = actor.init(
        jax.random.key(0), jnp.zeros((OBS_DIM,)), jax.random.key(1)
    )
    obs_spec = jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32)
    obs = np.ones((OBS_DIM,), np.float32)
    # Small per-forward capacity (2 rows x service_ms) so ONE replica
    # saturates well below the client herd's closed-loop offer rate —
    # otherwise a single replica absorbs the whole herd and scaling
    # measures the clients, not the fleet.
    max_batch = 2
    service_s = service_ms / 1e3
    out = {
        "simulated_service_ms": service_ms,
        "max_batch": max_batch,
        "backend": jax.default_backend(),
        "local_devices": len(jax.local_devices()),
        "replicas": {},
    }

    def slow_engines(fleet):
        """Pin each replica engine's forward to the simulated service
        time (the sleep releases the GIL, so replicas overlap exactly
        as N real devices would)."""
        for rep in fleet._replicas:
            engine, _, _ = rep.registry.acquire("default")
            real_act = engine.act

            def slow_act(*a, _real=real_act, **k):
                time.sleep(service_s)
                return _real(*a, **k)

            engine.act = slow_act

    def herd_window(act_fn, n_threads, window_s):
        """Closed-loop saturation: goodput over a fixed window."""
        stop = threading.Event()
        done = [0] * n_threads
        errors = []

        def worker(i):
            while not stop.is_set():
                try:
                    act_fn(obs)
                    done[i] += 1
                except Exception as e:  # noqa: BLE001 — recorded
                    errors.append(repr(e)[:200])
                    return
        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(n_threads)
        ]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        time.sleep(window_s)
        stop.set()
        for th in threads:
            th.join(timeout=60.0)
        return sum(done), time.perf_counter() - t0, errors

    n_threads = 32
    window_s = min(6.0, budget_s / 8)
    goodput_by_n = {}
    for n in replica_counts:
        if n > len(jax.local_devices()):
            out["replicas"][str(n)] = {
                "skipped": f"only {len(jax.local_devices())} local devices"
            }
            continue
        registry = ModelRegistry()
        registry.register(
            "default", actor, obs_spec, params=params,
            max_batch=max_batch,
        )
        metrics = ServeMetrics()
        with EngineFleet(
            registry, devices=n, max_batch=max_batch, max_wait_ms=1.0,
            metrics=metrics, capacity=1024,
        ) as fleet:
            fleet.warmup()
            slow_engines(fleet)
            fleet.act(obs, timeout=30.0)  # rinse
            answered, elapsed, errors = herd_window(
                lambda o: fleet.act(o, timeout=30.0), n_threads, window_s
            )
            snap = metrics.snapshot()
            entry = {
                "goodput_rps": round(answered / elapsed, 1),
                "p50_ms": snap.get("p50_ms"),
                "p99_ms": snap.get("p99_ms"),
                "mean_batch_occupancy": snap.get("mean_batch_occupancy"),
                "dispatch_share": [
                    s["dispatched_total"] for s in fleet.replica_stats()
                ],
            }
            if errors:
                entry["errors"] = errors[:3]
            goodput_by_n[n] = answered / elapsed
            out["replicas"][str(n)] = entry
            log(f"fleet x{n}: {entry['goodput_rps']} rps, "
                f"p99 {entry['p99_ms']}ms, "
                f"dispatch {entry['dispatch_share']}")
        registry.close()
    if 1 in goodput_by_n:
        out["scaling_vs_1"] = {
            str(n): round(goodput_by_n[n] / goodput_by_n[1], 2)
            for n in goodput_by_n if n != 1
        }

    # Continuous vs group batching at LOW offered load (single
    # replica): group mode holds a lone request max_wait_ms hoping for
    # company; continuous dispatches it the moment the engine is free.
    # The acceptance bar is continuous p50 <= group p50 here.
    max_wait_ms = 10.0
    paced_interval = 0.025  # ~40 rps offered, far below service rate
    low_load = {}
    for mode in ("group", "continuous"):
        if time.time() - t_start > budget_s - 15:
            break
        registry = ModelRegistry()
        registry.register(
            "default", actor, obs_spec, params=params,
            max_batch=max_batch,
        )
        metrics = ServeMetrics()
        with MicroBatcher(
            registry, max_batch=max_batch, max_wait_ms=max_wait_ms,
            metrics=metrics, mode=mode,
        ) as mb:
            engine, _, _ = registry.acquire("default")
            real_act = engine.act

            def slow_act(*a, _real=real_act, **k):
                time.sleep(service_s)
                return _real(*a, **k)

            engine.act = slow_act
            mb.act(obs, timeout=30.0)  # rinse
            t_end = time.perf_counter() + min(4.0, budget_s / 10)
            while time.perf_counter() < t_end:
                mb.act(obs, timeout=30.0)
                time.sleep(paced_interval)
            low_load[mode] = metrics.snapshot().get("p50_ms")
        registry.close()
    out["low_load_p50_ms"] = dict(
        low_load, max_wait_ms=max_wait_ms,
        offered_rps=round(1.0 / paced_interval, 1),
    )
    if len(low_load) == 2:
        log(f"fleet low-load p50: group {low_load['group']}ms vs "
            f"continuous {low_load['continuous']}ms")
    return out


def bench_sharded_serving(
    budget_s=180.0,
    submeshes=((1, 1), (2, 1), (2, 2)),
    precisions=("f32", "bf16", "int8"),
):
    """Sub-mesh serving sweep (docs/SERVING.md "Sharded serving &
    precision tiers"): goodput/p99 through the REAL sub-mesh
    EngineFleet for submesh {1x1, 2x1, 2x2} x precision {f32, bf16,
    int8} on the local (forced, on CPU) devices. The CPU numbers
    measure the dispatch+placement plane — whether carving devices
    into sub-meshes or switching tiers adds host-side serialization —
    plus the per-replica reload transfer bytes each layout actually
    moves; chip MFU deltas for the tiers are TPU artifacts
    (bench.py runs on-chip pick them up via the same stage)."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.models import Actor
    from torch_actor_critic_tpu.serve import (
        EngineFleet,
        ModelRegistry,
        ServeMetrics,
    )

    t_start = time.time()
    actor = Actor(act_dim=ACT_DIM, hidden_sizes=HIDDEN)
    params = actor.init(
        jax.random.key(0), jnp.zeros((OBS_DIM,)), jax.random.key(1)
    )
    obs_spec = jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32)
    obs = np.ones((OBS_DIM,), np.float32)
    n_local = len(jax.local_devices())
    out = {
        "backend": jax.default_backend(),
        "local_devices": n_local,
        "combos": {},
    }

    def herd_window(act_fn, n_threads, window_s):
        stop = threading.Event()
        done = [0] * n_threads
        errors = []

        def worker(i):
            while not stop.is_set():
                try:
                    act_fn(obs)
                    done[i] += 1
                except Exception as e:  # noqa: BLE001 — recorded
                    errors.append(repr(e)[:200])
                    return

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(n_threads)
        ]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        time.sleep(window_s)
        stop.set()
        for th in threads:
            th.join(timeout=60.0)
        return sum(done), time.perf_counter() - t0, errors

    n_combos = len(submeshes) * len(precisions)
    window_s = max(1.0, min(3.0, budget_s / (n_combos * 3)))
    for tp, fsdp in submeshes:
        for precision in precisions:
            name = f"{tp}x{fsdp}_{precision}"
            if time.time() - t_start > budget_s - window_s - 5:
                out["combos"][name] = {"skipped": "stage budget"}
                continue
            per = tp * fsdp
            if per > n_local:
                out["combos"][name] = {
                    "skipped": f"needs {per} of {n_local} devices"
                }
                continue
            devices = jax.local_devices()[: (n_local // per) * per]
            registry = ModelRegistry()
            registry.register(
                "default", actor, obs_spec, params=params,
                max_batch=8, warmup=False,
            )
            metrics = ServeMetrics()
            try:
                with EngineFleet(
                    registry, devices=devices, max_batch=8,
                    metrics=metrics, submesh=(tp, fsdp),
                    precision=precision, fsdp_min_bytes=0,
                ) as fleet:
                    fleet.warmup()
                    fleet.act(obs, timeout=30.0)  # rinse
                    answered, elapsed, errors = herd_window(
                        lambda o: fleet.act(o, timeout=30.0),
                        n_threads=16, window_s=window_s,
                    )
                    snap = metrics.snapshot()
                    stats = fleet.sharding_stats()
                    entry = {
                        "replicas": fleet.n_replicas,
                        "goodput_rps": round(answered / elapsed, 1),
                        "p50_ms": snap.get("p50_ms"),
                        "p99_ms": snap.get("p99_ms"),
                        "reload_transfer_bytes_per_replica": (
                            stats["per_replica"][0]["last_transfer_bytes"]
                        ),
                    }
                    if errors:
                        entry["errors"] = errors[:3]
                    out["combos"][name] = entry
                    log(
                        f"sharded {name}: {entry['replicas']} replicas, "
                        f"{entry['goodput_rps']} rps, "
                        f"p99 {entry['p99_ms']}ms, "
                        f"{entry['reload_transfer_bytes_per_replica']}B/"
                        "replica reload"
                    )
            except Exception as e:  # noqa: BLE001 — one combo's
                # failure must not void the sweep
                out["combos"][name] = {"error": repr(e)[:200]}
            finally:
                registry.close()
    return out


def bench_telemetry_overhead(budget_s=420.0):
    """Telemetry cost (docs/OBSERVABILITY.md zero-overhead contract):
    steady-state Trainer throughput with telemetry off vs on (full
    phase spans + span ring + JSONL sink + per-epoch HBM sampling) at a
    tiny CPU config, plus a recorder microbenchmark (ns per lap). The
    acceptance bar is enabled-mode within 5% of disabled-mode."""
    import tempfile

    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.telemetry import TelemetryRecorder
    from torch_actor_critic_tpu.utils.config import SACConfig

    t_start = time.time()
    out = {}

    # Recorder microbenchmark: the per-mark cost an enabled hot loop
    # pays (monotonic read + list accumulate + ring store).
    rec = TelemetryRecorder()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        rec.lap(0)
    out["lap_ns"] = round((time.perf_counter() - t0) / n * 1e9, 1)

    from torch_actor_critic_tpu.utils.tracking import Tracker

    tiny = dict(
        hidden_sizes=(32, 32), batch_size=32, epochs=4,
        steps_per_epoch=400, start_steps=50, update_after=50,
        update_every=50, buffer_size=5000, max_ep_len=200,
    )
    # ABBA order: slow drift (CPU frequency, cache state, background
    # load) biases a plain off-then-on comparison in whichever
    # direction the drift runs; interleaving cancels it to first order.
    rates: dict = {"off": [], "grad_off": [], "on": [], "grad_on": []}
    for mode in ("off", "on", "on", "off"):
        if time.time() - t_start > budget_s:
            break
        try:
            root = tempfile.mkdtemp(prefix="bench_tm_")
            tracker = Tracker(experiment="bench", root=root)
            telem = (
                TelemetryRecorder(run_dir=tracker.run_dir)
                if mode == "on" else None
            )
            tr = Trainer(
                "Pendulum-v1", SACConfig(**tiny), mesh=make_mesh(dp=1),
                tracker=tracker, telemetry=telem,
            )
            try:
                tr.train()
            finally:
                tr.close()
            # Post-warmup epochs only (epoch 0 pays the jit compiles);
            # the accounting fix already keeps every epoch's dt free of
            # save/sentinel time, on both sides of the comparison.
            rows = tracker.metrics()[1:]
            rates[mode].extend(r["env_steps_per_sec"] for r in rows)
            rates[f"grad_{mode}"].extend(
                r["grad_steps_per_sec"] for r in rows
            )
        except Exception as e:  # noqa: BLE001 — per-run best effort
            out.setdefault("errors", []).append(repr(e)[:200])
    # Best observed epoch per mode: scheduler hiccups only ever slow an
    # epoch down, so the max is the least-contended estimate of each
    # mode's true rate.
    for mode in ("off", "on"):
        if rates[mode]:
            out[mode] = {
                "env_steps_per_sec": round(max(rates[mode]), 1),
                "grad_steps_per_sec": round(max(rates[f"grad_{mode}"]), 1),
                "epoch_rates": [round(r, 1) for r in rates[mode]],
            }
    off = out.get("off", {}).get("env_steps_per_sec")
    on = out.get("on", {}).get("env_steps_per_sec")
    if off and on:
        out["overhead_pct"] = round((off - on) / off * 100, 2)
    log(f"telemetry overhead: {out}")
    return out


def bench_obs_overhead(budget_s=420.0):
    """Run-wide observability cost (docs/OBSERVABILITY.md "Run-wide
    plane"): steady-state Trainer throughput with the obs collector
    off vs on (scrape thread + learner source + SLO engine + obs.jsonl
    sink + per-epoch obs/ metric columns) at a tiny CPU config. Same
    ABBA discipline and 5% acceptance bar as telemetry_overhead — the
    collector lives on its own thread, so steady-state cost should be
    the learner-source snapshot plus a dict merge per epoch."""
    import tempfile

    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.tracking import Tracker

    t_start = time.time()
    out = {}
    tiny = dict(
        hidden_sizes=(32, 32), batch_size=32, epochs=4,
        steps_per_epoch=400, start_steps=50, update_after=50,
        update_every=50, buffer_size=5000, max_ep_len=200,
    )
    # ABBA order for the same reason as telemetry_overhead: slow drift
    # biases off-then-on; interleaving cancels it to first order.
    rates: dict = {"off": [], "grad_off": [], "on": [], "grad_on": []}
    for mode in ("off", "on", "on", "off"):
        if time.time() - t_start > budget_s:
            break
        try:
            root = tempfile.mkdtemp(prefix="bench_obs_")
            tracker = Tracker(experiment="bench", root=root)
            tr = Trainer(
                "Pendulum-v1",
                SACConfig(**tiny, obs=(mode == "on"), obs_interval_s=0.5),
                mesh=make_mesh(dp=1), tracker=tracker,
            )
            try:
                tr.train()
            finally:
                tr.close()
            rows = tracker.metrics()[1:]
            rates[mode].extend(r["env_steps_per_sec"] for r in rows)
            rates[f"grad_{mode}"].extend(
                r["grad_steps_per_sec"] for r in rows
            )
        except Exception as e:  # noqa: BLE001 — per-run best effort
            out.setdefault("errors", []).append(repr(e)[:200])
    # Max-of-post-warmup-epochs per mode (least-contended estimate),
    # matching telemetry_overhead's accounting.
    for mode in ("off", "on"):
        if rates[mode]:
            out[mode] = {
                "env_steps_per_sec": round(max(rates[mode]), 1),
                "grad_steps_per_sec": round(max(rates[f"grad_{mode}"]), 1),
                "epoch_rates": [round(r, 1) for r in rates[mode]],
            }
    off = out.get("off", {}).get("env_steps_per_sec")
    on = out.get("on", {}).get("env_steps_per_sec")
    if off and on:
        out["overhead_pct"] = round((off - on) / off * 100, 2)
    log(f"obs overhead: {out}")
    return out


def bench_elastic(budget_s=120.0, windows=600, window_s=1.0):
    """Elastic vs fixed fleet under a diurnal load curve
    (docs/RESILIENCE.md "Elasticity"): the REAL ElasticController
    drives a simulated fleet through two compressed day/night cycles
    and is scored against a fixed mean-provisioned fleet on the three
    axes the autoscaler trades — goodput, tail latency, and
    worker-seconds paid.

    Same philosophy as bench_fleet's simulated service time: the
    decision plane under test (breach -> spawn, green streak ->
    drain) is the production code path; only the workers are modeled
    (fixed per-replica service rate, carried queue, bounded backlog
    with shed), because on the 1-core bench host real workers would
    measure the host, not the controller. Simulated clock, so the
    whole curve costs milliseconds of wall time."""
    import math

    from torch_actor_critic_tpu.elastic import (
        DecisionLog,
        ElasticController,
        ElasticPolicy,
    )

    cap = 50.0          # req/s one replica serves
    base, peak = 20.0, 150.0
    period = windows / 2  # two diurnal cycles across the run

    def offered(w):
        phase = (1.0 + math.sin(2.0 * math.pi * w / period
                                - math.pi / 2.0)) / 2.0
        return base + (peak - base) * phase

    def run_config(elastic):
        sim_now = [0.0]

        class SimFleet:
            """The modeled worker plane: replicas x cap req/s, a
            carried queue bounded at one window of fleet capacity
            (beyond that requests shed, as the real admission plane
            would 503)."""

            def __init__(self, n):
                self.n = n
                self.queue = 0.0
                self.served = 0.0
                self.shed = 0.0
                self.worker_seconds = 0.0

            def replicas(self):
                return self.n

            def queue_depth(self):
                return self.queue

            def scale_out(self, reason=""):
                self.n += 1
                return {"outcome": "spawned", "worker": f"sim{self.n}"}

            def scale_in(self, reason=""):
                self.n -= 1
                return {"outcome": "draining"}

            def step(self, load):
                capacity = self.n * cap * window_s
                backlog = self.queue + load * window_s
                done = min(backlog, capacity)
                rest = backlog - done
                allowed = capacity  # one window of headroom
                self.served += done
                self.shed += max(0.0, rest - allowed)
                self.queue = min(rest, allowed)
                self.worker_seconds += self.n * window_s
                # Latency proxy: queueing delay in front of the fleet
                # plus a fixed service floor.
                wait_s = (self.queue / (self.n * cap)) if self.n else 0.0
                return 5.0 + wait_s * 1e3

        fleet = SimFleet(2)
        controller = None
        if elastic:
            controller = ElasticController(
                fleet,
                policy=ElasticPolicy(
                    min_replicas=1, max_replicas=4,
                    scale_out_cooldown_s=5.0,
                    scale_in_cooldown_s=30.0,
                    scale_in_ok_windows=10,
                ),
                log=DecisionLog(),
                clock=lambda: sim_now[0],
            )
        lat_ms = []
        breached = False
        bad = 0
        ok = 0
        for w in range(windows):
            load = offered(w)
            lat_ms.append(fleet.step(load))
            # The goodput-floor hysteresis the obs SLO engine would
            # emit: falling behind the offered load for 2 windows
            # breaches, 2 caught-up windows recover.
            behind = fleet.queue > 0.5 * fleet.n * cap * window_s
            bad = bad + 1 if behind else 0
            ok = 0 if behind else ok + 1
            events = []
            if not breached and bad >= 2:
                breached = True
                events.append({"type": "slo_breach",
                               "rule": "goodput_floor"})
            elif breached and ok >= 2:
                breached = False
                events.append({"type": "slo_recovered",
                               "rule": "goodput_floor"})
            if controller is not None:
                controller.observe_window({"slo": {"events": events}})
            sim_now[0] += window_s
        lat_ms.sort()
        total = windows * window_s
        row = {
            "goodput_rps": round(fleet.served / total, 1),
            "p99_ms": round(lat_ms[int(0.99 * (len(lat_ms) - 1))], 1),
            "worker_seconds": round(fleet.worker_seconds, 1),
            "shed_total": round(fleet.shed, 1),
            "final_replicas": fleet.n,
        }
        if controller is not None:
            snap = controller.snapshot()
            row["scale_out_total"] = snap["scale_out_total"]
            row["scale_in_total"] = snap["scale_in_total"]
        return row

    out = {
        "windows": windows,
        "window_s": window_s,
        "replica_cap_rps": cap,
        "offered_rps": {"base": base, "peak": peak},
        "fixed": run_config(elastic=False),
        "elastic": run_config(elastic=True),
    }
    log_point("elastic", dict(out["fixed"], variant="fixed"))
    log_point("elastic", dict(out["elastic"], variant="elastic"))
    log(f"elastic bench: {out}")
    return out


def bench_replay(budget_s=300.0):
    """Tiered-replay throughput (docs/REPLAY.md): the host-side costs
    the tier stack adds around the (unchanged) device ring — waterfall
    ingest with spill, task-balanced refill sampling, disk-tier chunk
    append/sample on real files, and the ``--offline`` update burst.
    All keys are ``*_per_sec`` so ``make bench-diff`` treats drops as
    regressions."""
    import shutil
    import tempfile

    import numpy as np

    from torch_actor_critic_tpu.replay import (
        DiskTier,
        TieredReplay,
        rows_count,
    )

    t_start = time.time()
    out = {}
    obs_dim, act_dim, chunk_rows = 16, 4, 256

    def mk_rows(n, seed=0):
        rng = np.random.default_rng(seed)
        return {
            "states": rng.standard_normal((n, obs_dim)).astype(np.float32),
            "next_states": rng.standard_normal(
                (n, obs_dim)
            ).astype(np.float32),
            "actions": rng.standard_normal((n, act_dim)).astype(np.float32),
            "rewards": rng.standard_normal(n).astype(np.float32),
            "done": np.zeros(n, np.float32),
        }

    # --- waterfall ingest (HBM shadow -> host, every chunk spills) ----
    tiers = TieredReplay(hbm_capacity=1024, host_capacity=8192)
    chunk = mk_rows(chunk_rows)
    tiers.ingest_rows(chunk)  # allocate rings outside the timed region
    n_chunks, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < 2.0:
        tiers.ingest_rows(chunk)
        n_chunks += 1
    dt = time.perf_counter() - t0
    out["spill_rows_per_sec"] = round(n_chunks * chunk_rows / dt, 1)
    out["conservation_ok"] = bool(tiers.conservation_holds())

    # --- refill sampling off the host tier ----------------------------
    n_draws, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < 2.0:
        tiers.sample_refill(chunk_rows)
        n_draws += 1
    dt = time.perf_counter() - t0
    out["refill_rows_per_sec"] = round(n_draws * chunk_rows / dt, 1)

    # --- disk tier: npz chunk append + uniform sample on real files ---
    root = tempfile.mkdtemp(prefix="bench_replay_")
    try:
        disk = DiskTier(root)
        rng = np.random.default_rng(0)
        n_app, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 2.0 and n_app < 512:
            disk.append(chunk)
            n_app += 1
        dt = time.perf_counter() - t0
        out["disk_append_rows_per_sec"] = round(n_app * chunk_rows / dt, 1)
        n_draws, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 2.0:
            got = disk.sample(rng, chunk_rows)
            n_draws += 1
        dt = time.perf_counter() - t0
        assert rows_count(got) == chunk_rows
        out["disk_sample_rows_per_sec"] = round(
            n_draws * chunk_rows / dt, 1
        )
        disk.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # --- offline burst (the --offline jit program) --------------------
    if time.time() - t_start < budget_s - 30:
        try:
            import jax

            from torch_actor_critic_tpu.replay.offline import (
                OfflineLearner,
                _stack_batches,
            )
            from torch_actor_critic_tpu.utils.config import SACConfig

            cfg = SACConfig(
                hidden_sizes=(64, 64), batch_size=64, offline=True,
                offline_dataset="unused", offline_steps=100,
            )
            spec = jax.ShapeDtypeStruct((obs_dim,), np.float32)
            learner = OfflineLearner(cfg, spec, act_dim)
            state = learner.init_state(jax.random.PRNGKey(0))
            data = mk_rows(4096)
            sampler = np.random.default_rng(0)
            burst = 20
            batches = _stack_batches(data, sampler, burst, cfg.batch_size)
            state, _ = learner.burst(state, batches)  # compile
            steps, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < 10.0:
                batches = _stack_batches(
                    data, sampler, burst, cfg.batch_size
                )
                state, metrics = learner.burst(state, batches)
                steps += burst
            jax.block_until_ready(metrics)
            dt = time.perf_counter() - t0
            out["offline_grad_steps_per_sec"] = round(steps / dt, 1)
        except Exception as e:  # noqa: BLE001 — per-section best effort
            out.setdefault("errors", []).append(repr(e)[:200])
    log(f"replay: {out}")
    return out


def bench_sanitize_overhead(budget_s=420.0):
    """Transfer-sanitizer cost (docs/ANALYSIS.md "Runtime sanitizers"):
    steady-state Trainer throughput with --sanitize off vs on at the
    tiny CPU config. The off tier must be free by construction (one
    pointer check per guarded site); the on tier's entire cost is two
    transfer-guard context entries per update window plus the explicit
    drain fetch, so BOTH sides of the comparison are held to the same
    5% bar the telemetry/diagnostics stages use."""
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.tracking import Tracker

    import tempfile

    t_start = time.time()
    out: dict = {}
    tiny = dict(
        hidden_sizes=(32, 32), batch_size=32, epochs=4,
        steps_per_epoch=400, start_steps=50, update_after=50,
        update_every=50, buffer_size=5000, max_ep_len=200,
        save_every=1000, sentinel=False,
    )
    # ABBA order, like the telemetry/diagnostics overhead stages: slow
    # host drift cancels to first order.
    rates: dict = {"off": [], "grad_off": [], "on": [], "grad_on": []}
    for mode in ("off", "on", "on", "off"):
        if time.time() - t_start > budget_s:
            break
        try:
            root = tempfile.mkdtemp(prefix="bench_san_")
            tracker = Tracker(experiment="bench", root=root)
            tr = Trainer(
                "Pendulum-v1", SACConfig(**tiny, sanitize=mode),
                mesh=make_mesh(dp=1), tracker=tracker,
            )
            try:
                tr.train()
            finally:
                tr.close()
            rows = tracker.metrics()[1:]  # epoch 0 pays the compiles
            rates[mode].extend(r["env_steps_per_sec"] for r in rows)
            rates[f"grad_{mode}"].extend(
                r["grad_steps_per_sec"] for r in rows
            )
        except Exception as e:  # noqa: BLE001 — per-run best effort
            out.setdefault("errors", []).append(repr(e)[:200])
    for mode in ("off", "on"):
        if rates[mode]:
            out[mode] = {
                "env_steps_per_sec": round(max(rates[mode]), 1),
                "grad_steps_per_sec": round(max(rates[f"grad_{mode}"]), 1),
                "epoch_rates": [round(r, 1) for r in rates[mode]],
            }
    off = out.get("off", {}).get("env_steps_per_sec")
    on = out.get("on", {}).get("env_steps_per_sec")
    if off and on:
        out["overhead_pct"] = round((off - on) / off * 100, 2)
    log(f"sanitize overhead: {out}")
    return out


def bench_decoupled(budget_s=420.0, max_actor_lag=4):
    """Decoupled actor/learner cost at equal config (docs/RESILIENCE.md
    "Decoupled-plane failure modes"): steady-state env-steps/s and
    grad-steps/s of the lockstep Trainer vs the DecoupledTrainer —
    every policy action through the real registry/batcher/client stack,
    transitions through the staging gate — plus the observed staleness
    distribution against ``--max-actor-lag`` (steady-state inline lag
    is exactly one publish). The delta IS the serving-plane toll on the
    act path; bench-diff picks the throughput keys up via its existing
    ``*_per_sec`` directions."""
    from torch_actor_critic_tpu.decoupled import DecoupledTrainer
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig

    t_start = time.time()
    tiny = dict(
        hidden_sizes=(32, 32), batch_size=32, epochs=4,
        steps_per_epoch=400, start_steps=50, update_after=50,
        update_every=50, buffer_size=5000, max_ep_len=200,
        save_every=1000, sentinel=False,
    )
    out: dict = {"config": dict(tiny, max_actor_lag=max_actor_lag)}
    # ABBA order, like the telemetry/diagnostics overhead stages: slow
    # host drift cancels to first order.
    rates: dict = {m: [] for m in (
        "lockstep", "grad_lockstep", "decoupled", "grad_decoupled",
    )}
    lag_snap = None
    for mode in ("lockstep", "decoupled", "decoupled", "lockstep"):
        if time.time() - t_start > budget_s:
            break
        try:
            if mode == "decoupled":
                cfg = SACConfig(
                    **tiny, decoupled=True, max_actor_lag=max_actor_lag
                )
                tr = DecoupledTrainer(
                    "Pendulum-v1", cfg, mesh=make_mesh(dp=1), seed=0
                )
            else:
                tr = Trainer(
                    "Pendulum-v1", SACConfig(**tiny),
                    mesh=make_mesh(dp=1), seed=0,
                )
            epoch_rates, epoch_grad = [], []
            real_hook = tr._epoch_boundary_hook

            def hook(e, ok, saved, metrics, rec, _real=real_hook):
                _real(e, ok, saved, metrics, rec)
                epoch_rates.append(metrics["env_steps_per_sec"])
                epoch_grad.append(metrics["grad_steps_per_sec"])

            tr._epoch_boundary_hook = hook
            try:
                tr.train()
                if mode == "decoupled":
                    lag_snap = tr.staging.snapshot()["actor_lag"]
            finally:
                tr.close()
            # Post-warmup epochs only (epoch 0 pays the jit compiles).
            rates[mode].extend(epoch_rates[1:])
            rates[f"grad_{mode}"].extend(epoch_grad[1:])
        except Exception as e:  # noqa: BLE001 — per-run best effort
            out.setdefault("errors", []).append(repr(e)[:200])
    for mode in ("lockstep", "decoupled"):
        if rates[mode]:
            out[f"{mode}_env_steps_per_sec"] = round(max(rates[mode]), 1)
            out[f"{mode}_grad_steps_per_sec"] = round(
                max(rates[f"grad_{mode}"]), 1
            )
    a = out.get("lockstep_env_steps_per_sec")
    b = out.get("decoupled_env_steps_per_sec")
    if a and b:
        out["decoupling_overhead_pct"] = round((a - b) / a * 100, 2)
    if lag_snap is not None:
        out["actor_lag"] = lag_snap
        out["max_actor_lag"] = max_actor_lag
        out["lag_bounded"] = (
            lag_snap.get("actor_lag_max", 0.0) <= max_actor_lag
        )
    log(f"decoupled: {out}")
    return out


def bench_actor_fleet(budget_s=240.0, sizes=(1, 2, 4), max_actor_lag=4):
    """Actor-fleet scaling (docs/RESILIENCE.md "Decoupled-plane failure
    modes"): learner throughput and staleness as ``--actors N`` fleet
    actors feed the staging buffer over the real networked transport
    (HTTP push, per-actor seq dedup). Actors run on threads through the
    exact ``_actor_loop`` the subprocess shim runs — same wire path,
    same heartbeats, without charging each sweep point a fresh jax
    import — so the curve isolates the transport + contention cost.
    bench-diff picks up the per-size ``*_per_sec`` keys."""
    import threading

    from torch_actor_critic_tpu.decoupled import FleetTrainer
    from torch_actor_critic_tpu.decoupled.fleet import _actor_loop
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.utils.config import SACConfig

    t_start = time.time()
    tiny = dict(
        hidden_sizes=(32, 32), batch_size=32, epochs=3,
        steps_per_epoch=400, start_steps=50, update_after=50,
        update_every=50, buffer_size=5000, max_ep_len=200,
        save_every=1000, sentinel=False,
    )
    out: dict = {
        "config": dict(tiny, max_actor_lag=max_actor_lag),
        "sizes": list(sizes),
    }

    class _ThreadProc:
        _pid = iter(range(2 ** 24, 2 ** 25))

        def __init__(self, body):
            self.pid = next(self._pid)
            self.exitcode = None
            self.stop = threading.Event()
            self._t = threading.Thread(
                target=body, args=(self.stop,), daemon=True
            )
            self._t.start()

        def is_alive(self):
            return self._t.is_alive()

        def join(self, timeout=None):
            self.stop.set()
            self._t.join(timeout)

    for n in sizes:
        if time.time() - t_start > budget_s:
            out.setdefault("skipped_sizes", []).append(n)
            log(f"actor_fleet: budget exhausted, skipping actors={n}")
            continue
        try:
            cfg = SACConfig(
                **tiny, actors=n, staging_policy="shed",
                max_actor_lag=max_actor_lag, heartbeat_timeout_s=30.0,
            )
            holder: dict = {}

            def spawn(aid, inc, _h=holder):
                return _ThreadProc(lambda stop: _actor_loop(
                    aid, inc, _h["tr"].transport.address,
                    "Pendulum-v1", 1, 3000 + 10 * aid + inc, stop,
                    options={"heartbeat_interval_s": 0.5,
                             "push_retry_s": 1.0},
                ))

            tr = FleetTrainer(
                "Pendulum-v1", cfg, mesh=make_mesh(dp=1), seed=0,
                spawn=spawn,
            )
            holder["tr"] = tr
            epoch_rates, epoch_grad = [], []
            real_hook = tr._epoch_boundary_hook

            def hook(e, ok, saved, metrics, rec, _real=real_hook):
                _real(e, ok, saved, metrics, rec)
                epoch_rates.append(metrics["env_steps_per_sec"])
                epoch_grad.append(metrics["grad_steps_per_sec"])

            tr._epoch_boundary_hook = hook
            try:
                tr.train()
                lag = tr.staging.snapshot()["actor_lag"]
                tsnap = tr.transport.snapshot()
                conserved = tr.staging.conservation_holds()
            finally:
                tr.close()
            # Post-warmup epochs only (epoch 0 pays the jit compiles).
            out[f"actors{n}_env_steps_per_sec"] = round(
                max(epoch_rates[1:] or epoch_rates), 1
            )
            out[f"actors{n}_grad_steps_per_sec"] = round(
                max(epoch_grad[1:] or epoch_grad), 1
            )
            out[f"actors{n}_lag"] = lag
            out[f"actors{n}_transport_accepted"] = tsnap[
                "accepted_total"
            ]
            out[f"actors{n}_conserved"] = bool(conserved)
        except Exception as e:  # noqa: BLE001 — per-size best effort
            out.setdefault("errors", []).append(
                f"actors={n}: {e!r}"[:200]
            )
    log(f"actor_fleet: {out}")
    return out


def bench_coldstart(budget_s=420.0, trials=2):
    """Cold-start latency (docs/SERVING.md "Cold start & warm-start
    bundles"): time-to-first-act of a FRESH ``serve.py`` worker process
    without vs with a warm-start bundle (aot/bundle.py) and its
    pre-populated persistent compilation cache. Each point spawns the
    real operator CLI against a real checkpoint and times
    spawn -> ready (startup JSON line) and spawn -> first completed
    ``/act`` round-trip; the bundle rows read ``/metrics`` back to pin
    the serve-plane compile counters (``live_compiles`` must be 0 when
    the bundle loads). The ``*_ms`` keys ride bench-diff's existing
    lower-is-better direction; ``coldstart_speedup`` and
    ``cache_hit_rate`` are higher-better."""
    import shutil
    import tempfile
    from urllib import request as urlreq

    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.aot import emit_bundle
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    t_start = time.time()
    max_batch = 8
    tmp = tempfile.mkdtemp(prefix="bench_coldstart_")
    ckpt_dir = os.path.join(tmp, "ckpts")
    cfg = SACConfig(hidden_sizes=(32, 32))
    sac = SAC(cfg, Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32)),
              DoubleCritic(hidden_sizes=(32, 32)), ACT_DIM)
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    ck = Checkpointer(ckpt_dir, save_buffer=False)
    ck.save(0, state, extra={"config": cfg.to_json()}, wait=True)
    ck.close()

    t0 = time.time()
    emit_bundle(
        ckpt_dir, sac.actor_def,
        jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32),
        jax.device_get(state.actor_params), max_batch=max_batch,
    )
    out: dict = {
        "config": {"hidden": [32, 32], "max_batch": max_batch,
                   "trials": trials},
        "bundle_build_s": round(time.time() - t0, 2),
    }

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    if jax.default_backend() == "cpu":
        # Same subprocess hygiene as scripts/serve_smoke.py: the bundle
        # fingerprint was minted on CPU, so the worker must come up on
        # CPU too or every warm row silently measures the fallback.
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""

    def measure(extra, label):
        """Spawn one fresh worker; time ready + first /act; read the
        compile counters back; always reap the subprocess."""
        argv = [
            sys.executable, os.path.join(repo, "serve.py"),
            "--ckpt-dir", ckpt_dir,
            "--obs-dim", str(OBS_DIM), "--act-dim", str(ACT_DIM),
            "--port", "0", "--max-batch", str(max_batch),
            "--max-wait-ms", "2",
        ] + extra
        t_spawn = time.time()
        proc = subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, cwd=repo,
        )
        try:
            address, deadline = None, time.time() + 240
            while time.time() < deadline:
                line = proc.stdout.readline()
                if not line:
                    if proc.poll() is not None:
                        raise RuntimeError(
                            f"{label}: worker died rc={proc.returncode}"
                        )
                    time.sleep(0.05)
                    continue
                if line.startswith("{"):
                    try:
                        address = json.loads(line)["serving"]
                        break
                    except (json.JSONDecodeError, KeyError):
                        continue
            if address is None:
                raise RuntimeError(f"{label}: worker never became ready")
            ready_s = time.time() - t_spawn
            req = urlreq.Request(
                address + "/act",
                data=json.dumps(
                    {"obs": [0.0] * OBS_DIM, "deterministic": True}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            urlreq.urlopen(req, timeout=60).read()
            first_act_s = time.time() - t_spawn
            met = json.loads(
                urlreq.urlopen(address + "/metrics", timeout=30).read()
            )
            xla = met.get("xla", {})
            row = {
                "ready_ms": round(ready_s * 1e3, 1),
                "first_act_ms": round(first_act_s * 1e3, 1),
                "live_compiles": met.get("live_compiles"),
                "bundle_compiles": met.get("bundle_compiles"),
                "warmup_compiles": xla.get("warmup_compiles"),
                "bundle_load_compiles": xla.get("bundle_load_compiles"),
                "bundle_hits": xla.get("bundle_hits"),
                "bundle_rejected": xla.get("bundle_rejected"),
                "cache_hits": xla.get("cache_hits_total"),
                "cache_misses": xla.get("cache_misses_total"),
            }
            hits, misses = row["cache_hits"], row["cache_misses"]
            if hits is not None and misses is not None and hits + misses:
                row["cache_hit_rate"] = round(hits / (hits + misses), 3)
            return row
        finally:
            proc.terminate()
            try:
                proc.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()

    # ABBA order like the overhead stages: host drift (page cache,
    # thermal) cancels to first order across the cold/warm pairs.
    rows: dict = {"cold": [], "warm": []}
    for label in (["cold", "warm", "warm", "cold"] * trials)[: 2 * trials]:
        if (time.time() - t_start > budget_s
                and rows["cold"] and rows["warm"]):
            break
        extra = ["--warm-start", "auto"] if label == "warm" else []
        try:
            row = measure(extra, label)
            rows[label].append(row)
            log_point("coldstart", dict(row, variant=label))
        except Exception as e:  # noqa: BLE001 — per-trial best effort
            out.setdefault("errors", []).append(f"{label}: {e!r}"[:200])
    shutil.rmtree(tmp, ignore_errors=True)

    best_cold = best_warm = None
    if rows["cold"]:
        best_cold = min(rows["cold"], key=lambda r: r["first_act_ms"])
        out["cold"] = best_cold
        out["cold_first_act_ms"] = best_cold["first_act_ms"]
    if rows["warm"]:
        best_warm = min(rows["warm"], key=lambda r: r["first_act_ms"])
        out["warm"] = best_warm
        out["warm_first_act_ms"] = best_warm["first_act_ms"]
        out["warm_live_compiles"] = best_warm["live_compiles"]
        if best_warm.get("cache_hit_rate") is not None:
            out["cache_hit_rate"] = best_warm["cache_hit_rate"]
    if best_cold and best_warm:
        out["coldstart_speedup"] = round(
            best_cold["first_act_ms"]
            / max(best_warm["first_act_ms"], 1e-9), 2
        )
        # The acceptance pin, recorded in the artifact itself: a fresh
        # worker answering its first /act off the bundle paid ZERO live
        # compiles (and really loaded the bundle — not the fallback).
        out["zero_live_compiles_with_bundle"] = bool(
            best_warm["live_compiles"] == 0
            and (best_warm["bundle_compiles"] or 0) > 0
        )
    log(f"coldstart: {out}")
    return out


def bench_diagnostics_overhead(budget_s=540.0):
    """Learning-health diagnostics cost (docs/OBSERVABILITY.md
    "Learning-health diagnostics"): steady-state Trainer throughput at
    each tier — off (parity), light (scalar grad/Q/saturation
    reductions fused into the burst) and full (light + the on-device
    TD-error histogram) — on the tiny CPU config. Acceptance bar:
    `light` within 5% of `off` (same bar as `telemetry_overhead`)."""
    import tempfile

    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.tracking import Tracker

    t_start = time.time()
    out = {}
    tiny = dict(
        hidden_sizes=(32, 32), batch_size=32, epochs=4,
        steps_per_epoch=400, start_steps=50, update_after=50,
        update_every=50, buffer_size=5000, max_ep_len=200,
    )
    # ABBA-ordered tiers (off..full then mirrored) so slow host drift
    # cancels to first order, exactly like the telemetry stage.
    rates: dict = {
        m: [] for tier in ("off", "light", "full")
        for m in (tier, f"grad_{tier}")
    }
    for tier in ("off", "light", "full", "full", "light", "off"):
        if time.time() - t_start > budget_s:
            break
        try:
            root = tempfile.mkdtemp(prefix="bench_diag_")
            tracker = Tracker(experiment="bench", root=root)
            tr = Trainer(
                "Pendulum-v1", SACConfig(**tiny, diagnostics=tier),
                mesh=make_mesh(dp=1), tracker=tracker,
            )
            try:
                tr.train()
            finally:
                tr.close()
            rows = tracker.metrics()[1:]  # post-warmup epochs only
            rates[tier].extend(r["env_steps_per_sec"] for r in rows)
            rates[f"grad_{tier}"].extend(
                r["grad_steps_per_sec"] for r in rows
            )
        except Exception as e:  # noqa: BLE001 — per-run best effort
            out.setdefault("errors", []).append(repr(e)[:200])
    # Best observed epoch per tier (scheduler hiccups only slow epochs
    # down, so the max is the least-contended estimate).
    for tier in ("off", "light", "full"):
        if rates[tier]:
            out[tier] = {
                "env_steps_per_sec": round(max(rates[tier]), 1),
                "grad_steps_per_sec": round(max(rates[f"grad_{tier}"]), 1),
                "epoch_rates": [round(r, 1) for r in rates[tier]],
            }
    off = out.get("off", {}).get("env_steps_per_sec")
    for tier in ("light", "full"):
        on = out.get(tier, {}).get("env_steps_per_sec")
        if off and on:
            out[f"overhead_{tier}_pct"] = round((off - on) / off * 100, 2)
    log(f"diagnostics overhead: {out}")
    return out


def bench_torch_cpu(n_steps=300):
    """Reference-style torch-CPU SAC update, timed per gradient step
    incl. uniform replay sampling — the measured stand-in for the
    unpublished reference baseline. Same shared implementation as the
    return-parity runs (``baselines/torch_sac.py``), so the throughput
    and return baselines can never drift apart."""
    import torch

    from torch_actor_critic_tpu.baselines import build_torch_sac

    _, update = build_torch_sac(OBS_DIM, ACT_DIM, hidden=HIDDEN)

    n = 100_000
    data = {
        "s": torch.randn(n, OBS_DIM),
        "a": torch.tanh(torch.randn(n, ACT_DIM)),
        "r": torch.randn(n),
        "s2": torch.randn(n, OBS_DIM),
        "d": torch.zeros(n),
    }

    def step():
        idx = torch.randint(0, n, (BATCH,))
        update(*(data[k][idx] for k in ("s", "a", "r", "s2", "d")))

    for _ in range(20):  # warmup
        step()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        step()
    return n_steps / (time.perf_counter() - t0)


def peak_flops_for(device_kind):
    from torch_actor_critic_tpu.telemetry.costmodel import (
        peak_flops_for as _peak_flops_for,
    )

    return _peak_flops_for(device_kind)


def mfu_metrics(acc_sps, device_kind, flops=None):
    """Achieved-FLOPs/MFU keys for a measured steps/sec number — shared
    by main(), the visual section and scripts/tpu_capture.py so driver
    JSON lines and persisted chip artifacts compute these identically.
    ``flops`` defaults to the flat headline's analytic per-step cost."""
    flops = sac_flops_per_step() if flops is None else flops
    out = {
        "flops_per_step": flops,
        "achieved_flops_per_sec": round(acc_sps * flops, 0),
    }
    peak = peak_flops_for(device_kind)
    if peak:
        out["mfu"] = round(acc_sps * flops / peak, 5)
        out["peak_flops_assumed"] = peak
    return out


def torch_baseline_metrics(diagnostics):
    """Measure the torch-CPU baseline (pinned fallback on failure);
    returns ``(torch_sps, keys_dict)``. Shared with tpu_capture.py."""
    try:
        torch_sps = bench_torch_cpu()
        return torch_sps, {"torch_cpu_steps_per_sec": round(torch_sps, 1)}
    except Exception as e:  # noqa: BLE001
        diagnostics.append({"torch_baseline_error": repr(e)})
        return TORCH_CPU_FALLBACK_SPS, {
            "torch_cpu_steps_per_sec": TORCH_CPU_FALLBACK_SPS,
            "torch_baseline_source": "pinned_fallback",
        }


def _stage_headline():
    """Subprocess entry: headline (parity-config, float32) number."""
    return {"acc_sps": bench_accelerator()}


def _stage_headline_bf16():
    """Subprocess entry: the same burst with compute_dtype=bfloat16
    (MXU-native matmuls, f32 params/optimizer/losses). Its own stage so
    a bf16 hang cannot cost the already-measured f32 headline."""
    return {"acc_sps_bf16": bench_accelerator(compute_dtype="bfloat16")}


_STAGES = {
    "headline": _stage_headline,
    "headline_bf16": _stage_headline_bf16,
    # sweep/unroll/td3 budget-scale to the enforced stage timeout
    # (stage_budget) — the BENCH_r05 fix: a chip snapshot completes
    # inside --stage-timeout instead of shipping truncated artifacts.
    "sweep": lambda: {"sweep": bench_sweep(budget_s=stage_budget(600.0))},
    "sharding": lambda: {
        "sharding": bench_sharding(budget_s=stage_budget(420.0))
    },
    "unroll": lambda: {
        "burst_unroll": bench_unroll(budget_s=stage_budget(300.0))
    },
    "td3": lambda: {"td3": bench_td3(budget_s=stage_budget(300.0))},
    # Both population sub-stages share the one subprocess timeout
    # (720s in main()), so their internal budgets are trimmed to fit
    # alongside backend init + compiles.
    "population": lambda: {
        "population": bench_population(budget_s=300.0),
        # The fused sub-stage: whole Anakin epochs (acting included)
        # vmapped over the member axis, not just the update burst.
        "population_fused": bench_population_fused(budget_s=280.0),
    },
    "visual": lambda: {"visual": bench_visual(budget_s=stage_budget(300.0))},
    "serving": lambda: {"serving": bench_serving()},
    "overload": lambda: {"overload": bench_overload()},
    "fleet": lambda: {
        "fleet": bench_fleet(),
        # Sub-mesh serving sweep: submesh {1x1,2x1,2x2} x precision
        # {f32,bf16,int8} goodput/p99 + per-replica reload transfer
        # bytes, picked up by make bench-diff's goodput/_rps/_ms
        # directions.
        "fleet_sharded": bench_sharded_serving(
            budget_s=stage_budget(180.0)
        ),
    },
    "decoupled": lambda: {
        "decoupled": bench_decoupled(),
        # Actors-vs-throughput curves over the networked staging
        # transport (--actors {1,2,4}).
        "actor_fleet": bench_actor_fleet(
            budget_s=stage_budget(240.0)
        ),
    },
    # Time-to-first-act of a fresh serve.py worker with vs without a
    # warm-start bundle (aot/; docs/SERVING.md "Cold start &
    # warm-start bundles").
    "coldstart": lambda: {
        "coldstart": bench_coldstart(budget_s=stage_budget(420.0))
    },
    "host_envs": lambda: {"host_envs": bench_host_envs()},
    "telemetry_overhead": lambda: {
        "telemetry_overhead": bench_telemetry_overhead()
    },
    "obs_overhead": lambda: {"obs_overhead": bench_obs_overhead()},
    # Elastic vs fixed fleet over a simulated diurnal load curve
    # (the real ElasticController deciding; goodput/p99/worker-
    # seconds picked up by make bench-diff's direction rows).
    "elastic": lambda: {"elastic": bench_elastic()},
    "diagnostics_overhead": lambda: {
        "diagnostics_overhead": bench_diagnostics_overhead()
    },
    "sanitize_overhead": lambda: {
        "sanitize_overhead": bench_sanitize_overhead()
    },
    # Tiered-replay host-side costs + the --offline burst
    # (docs/REPLAY.md) — spill/refill/disk rows-per-sec and offline
    # grad-steps-per-sec for make bench-diff.
    "replay": lambda: {
        "replay": bench_replay(budget_s=stage_budget(300.0))
    },
    "on_device": lambda: {"on_device": bench_on_device()},
    # scenarios/ families (multi-agent / procedural / multi-task)
    # vs the pendulum baseline — ROADMAP item 3's perf evidence.
    "scenarios": lambda: {
        "scenarios": bench_scenarios(budget_s=stage_budget(300.0))
    },
    # Two sequence lengths: the O(block)-memory kernel's scaling story —
    # 4x the length = 16x the FLOPs at flat VMEM residency.
    "attention": lambda: {
        # 2k carries the block sweep (8 extra Pallas fwd+bwd compiles);
        # the budgets must fit the stage timeout (1200s) together.
        "attention": bench_attention(budget_s=780.0, t=2048,
                                     block_sweep=True),
        "attention_8k": bench_attention(budget_s=240.0, t=8192),
    },
}


def _run_stage_inprocess(name):
    """Child-process mode: run one stage, print one JSON line, exit 0."""
    if (
        name in ("sharding", "fleet")
        and os.environ.get("TAC_BENCH_CHILD_PLATFORM") == "cpu"
        and "host_platform_device_count" not in os.environ.get("XLA_FLAGS", "")
    ):
        # The mesh and fleet stages are meaningless on one device; on
        # the CPU fallback give this child the same forced-device shim
        # tier-1 uses (must precede the first jax import, which
        # happens in _ensure_platform below).
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        ).strip()
    # Honor the parent's preflight decision: if it fell back to CPU, a
    # fresh import here would still default to the (dead) accelerator.
    _ensure_platform(os.environ.get("TAC_BENCH_CHILD_PLATFORM"))
    try:
        result = _STAGES[name]()
    except Exception as e:  # noqa: BLE001 — structured over traceback
        result = {"error": repr(e)}
    print(json.dumps(result), flush=True)


def stage_timeout_override():
    """The per-stage hard-timeout override: ``--stage-timeout=SECS``
    on the CLI (or ``TAC_BENCH_STAGE_TIMEOUT`` in the env) replaces
    every stage's default timeout — BENCH_r05's sweep/unroll/td3
    deaths were opaque 900s strings because the knob did not exist."""
    for a in sys.argv[1:]:
        if a.startswith("--stage-timeout="):
            return float(a.split("=", 1)[1])
    env = os.environ.get("TAC_BENCH_STAGE_TIMEOUT")
    return float(env) if env else None


# Fraction of a stage's hard timeout its INTERNAL budget may use; the
# remainder covers backend init + the first compiles, which happen
# before any budget check can run.
_STAGE_BUDGET_FRAC = 0.7


def stage_budget(default_s: float) -> float:
    """A stage's internal time budget, scaled to the enforced timeout.

    BENCH_r05 shipped truncated sweep/unroll/td3 sections because the
    stages' internal budgets were fixed constants: under a smaller
    ``--stage-timeout`` (or on a tunnel where compiles eat the window)
    the parent's hard kill landed BEFORE the stage's own budget check,
    losing the final JSON line. The parent now exports the effective
    per-stage timeout (``TAC_BENCH_STAGE_BUDGET``, set in
    ``run_stage_subprocess``); stages budget against
    ``min(default, 0.7 * timeout)`` so they self-terminate — emitting
    their completed points — inside any enforced window.
    """
    env = os.environ.get("TAC_BENCH_STAGE_BUDGET")
    if not env:
        return default_s
    return min(default_s, _STAGE_BUDGET_FRAC * float(env))


def log_point(stage_key: str, entry):
    """Stream one completed per-point result to stderr as a structured
    ``[bench-point]`` line. If the parent's hard timeout kills the
    stage anyway, ``run_stage_subprocess`` reassembles these lines into
    a partial (but structured and diff-able) stage section instead of
    shipping opaque log tails."""
    print(
        "[bench-point] " + json.dumps({"stage": stage_key, "entry": entry}),
        file=sys.stderr, flush=True,
    )


def collect_points(streams) -> dict:
    """Parse ``[bench-point]`` lines out of a killed child's streams;
    returns ``{stage_key: [entries...]}``."""
    points: dict = {}
    for stream in streams:
        if not stream:
            continue
        text = (
            stream.decode(errors="replace")
            if isinstance(stream, bytes) else stream
        )
        for line in text.splitlines():
            marker = line.find("[bench-point] ")
            if marker < 0:
                continue
            try:
                rec = json.loads(line[marker + len("[bench-point] "):])
                points.setdefault(rec["stage"], []).append(rec["entry"])
            except (json.JSONDecodeError, KeyError, TypeError):
                continue
    return points


# Structured per-stage failure records accumulated across the run and
# published as the artifact's `stage_errors` key (satellite of the
# cost-attribution PR): each is {stage, error, elapsed_s, timeout_s,
# rc?, stderr_tail?, partial_output?}.
STAGE_ERRORS: list = []


def run_stage_subprocess(
    name, timeout_s, diagnostics, platform=None, stage_errors=None
):
    """Run a bench stage in a subprocess with a hard timeout.

    The round-1 bench died when the TPU backend failed at init; the
    preflight fixed that, but a tunnel that dies MID-bench (observed
    this round: preflight ok, then every TPU op hangs forever) would
    still wedge the parent. A subprocess + timeout turns any hang into
    a structured diagnostic instead of a lost round.

    Failures append a STRUCTURED record to ``stage_errors`` (stage
    name, elapsed, timeout, error, and the child's output tails — the
    per-point ``[bench]`` progress lines are the partial results a
    killed stage leaves behind) instead of the former opaque
    ``"timeout after 900s"`` strings merged from partial runs.
    """
    override = stage_timeout_override()
    if override is not None:
        timeout_s = override
    env = dict(os.environ)
    if platform:
        env["TAC_BENCH_CHILD_PLATFORM"] = platform
    # Tell the child its hard window so stage_budget() can scale the
    # stage's internal budget to finish (and print its JSON) inside it.
    env["TAC_BENCH_STAGE_BUDGET"] = str(timeout_s)
    # Persistent compilation cache across stage subprocesses: each stage
    # re-jits the same burst shapes, and on the flaky tunnel every
    # compile eats capture window. Harmless where unsupported.
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )

    def record(err, proc=None, partial=None):
        rec = {
            "stage": name,
            "error": err,
            "elapsed_s": round(time.time() - t0, 1),
            "timeout_s": timeout_s,
        }
        if proc is not None:
            rec["rc"] = proc.returncode
            if proc.stderr:
                rec["stderr_tail"] = proc.stderr[-500:]
        if partial:
            rec["partial_output"] = partial
        (stage_errors if stage_errors is not None else STAGE_ERRORS).append(
            rec
        )
        diagnostics.append({f"{name}_stage_error": err})

    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), f"--stage={name}"],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        if proc.returncode == 0 and line:
            return json.loads(line)
        record(f"exit code {proc.returncode} with no result line", proc=proc)
    except subprocess.TimeoutExpired as e:
        # The kill loses the child's final JSON line; its streamed
        # stderr progress ([bench] lines per completed point) is the
        # partial evidence that survives.
        partial = []
        for stream in (e.stdout, e.stderr):
            if stream:
                text = (
                    stream.decode(errors="replace")
                    if isinstance(stream, bytes) else stream
                )
                partial.extend(text.strip().splitlines()[-8:])
        record(f"timeout after {timeout_s:g}s", partial=partial or None)
        log(f"stage {name} timed out ({timeout_s:g}s) — tunnel hang?")
        # Per-point subdivision: reassemble the structured
        # [bench-point] lines the child streamed per completed point —
        # a killed sweep still contributes its finished rows to the
        # artifact (marked truncated), not just log tails.
        points = collect_points((e.stdout, e.stderr))
        if points:
            out = {}
            for key, entries in points.items():
                out[key] = entries
                out[f"{key}_truncated"] = True
            return out
    except Exception as e:  # noqa: BLE001
        record(repr(e))
    return None


def main():
    out = {
        "metric": "sac_grad_steps_per_sec",
        "value": None,
        "unit": "steps/sec",
        "vs_baseline": None,
    }
    diagnostics = []

    # 1. Preflight the accelerator (subprocess; cannot hang the parent).
    info, pf_diags = preflight_backend()
    _ensure_platform(info.get("platform"))
    out["backend"] = info.get("platform")
    out["device_kind"] = info.get("device_kind")
    if pf_diags:
        diagnostics.append({"preflight": pf_diags})

    # 2. Accelerator benchmark FIRST (the number that matters), in a
    # subprocess so a mid-bench tunnel hang cannot wedge the parent.
    acc_sps = None
    if info.get("platform") not in (None, "none"):
        res = run_stage_subprocess(
            "headline", 600, diagnostics, platform=info.get("platform")
        )
        if res and "acc_sps" in res:
            acc_sps = res["acc_sps"]
            out["value"] = round(acc_sps, 1)
            log(f"accelerator: {acc_sps:.1f} grad-steps/s ({info.get('platform')})")
        elif res:
            diagnostics.append({"accelerator_bench_error": res.get("error")})
            log(f"accelerator bench failed: {res.get('error')}")
        res = run_stage_subprocess(
            "headline_bf16", 600, diagnostics, platform=info.get("platform")
        )
        if res and "acc_sps_bf16" in res:
            out["value_bf16"] = round(res["acc_sps_bf16"], 1)
            log(f"accelerator bf16: {out['value_bf16']} grad-steps/s")
        elif res:
            diagnostics.append({"bf16_bench_error": res.get("error")})

    # 3. MFU (analytic FLOPs; negligible-elementwise approximation).
    out["flops_per_step"] = sac_flops_per_step()
    if acc_sps is not None:
        out.update(mfu_metrics(acc_sps, info.get("device_kind")))

    # 4./5. Accelerator scaling sections: the batch/width sweep and the
    # fused on-device loop measure chip behavior — on the CPU *fallback*
    # they are meaningless and can take tens of minutes on a 2-thread
    # host, delaying the JSON line past harness timeouts. Skip unless
    # on a real accelerator (TAC_BENCH_FULL=1 overrides for testing).
    full = info.get("platform") != "cpu" or os.environ.get("TAC_BENCH_FULL") == "1"
    if acc_sps is not None and full:
        # One subprocess per section: a hang or overrun in one loses
        # only that section's data, and each timeout covers its own
        # internal budget plus a fresh backend-init + compile.
        for stage, timeout_s in (
            # attention runs two lengths with 180s internal budgets
            # each; its timeout covers both plus init + compiles.
            ("sweep", 900), ("sharding", 540), ("unroll", 420),
            ("td3", 420),
            ("population", 720), ("on_device", 540), ("scenarios", 420),
            ("attention", 900),
        ):
            res = run_stage_subprocess(
                stage, timeout_s, diagnostics, platform=info.get("platform")
            )
            if res and "error" in res:
                # Route child failure to diagnostics — a top-level
                # "error" key is reserved for total bench failure.
                diagnostics.append({f"{stage}_stage_error": res.pop("error")})
            if res:
                out.update(res)

    # 5a. Visual (CNN) burst — BASELINE config 5's perf half. Runs on
    # any backend (the section records which); on the CPU fallback its
    # internal calibration keeps it to a couple of bursts, and the
    # tighter timeout keeps a slow 1-core host from delaying the line.
    if info.get("platform") not in (None, "none"):
        res = run_stage_subprocess(
            "visual",
            480 if info.get("platform") != "cpu" else 360,
            diagnostics,
            platform=info.get("platform"),
        )
        if res and "error" in res:
            diagnostics.append({"visual_stage_error": res.pop("error")})
        if res:
            out.update(res)

    # 5a'. Serving fan-out (serve/ micro-batcher + bucketed jit): runs
    # on whatever backend preflight chose — the batcher/queue overhead
    # it measures is host-side, and on a real chip the forward rides
    # the accelerator exactly as production serving would.
    serving_platform = (
        info.get("platform")
        if info.get("platform") not in (None, "none")
        else "cpu"
    )
    res = run_stage_subprocess(
        "serving", 420, diagnostics, platform=serving_platform
    )
    if res and "error" in res:
        diagnostics.append({"serving_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5a''. Overload containment (docs/SERVING.md "Overload &
    # degradation"): flood the same stack at 2x its calibrated
    # capacity with a bounded queue — records goodput vs shed rate and
    # that the queue bound held. Same backend as the serving stage.
    res = run_stage_subprocess(
        "overload", 420, diagnostics, platform=serving_platform
    )
    if res and "error" in res:
        diagnostics.append({"overload_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5a'''. Fleet scale-out (docs/SERVING.md "Fleet"): aggregate
    # goodput + p99 vs engine-replica count {1,2,4} through the real
    # EngineFleet at a pinned simulated service time (the dispatch
    # plane is what scales; on CPU the child gets the forced-device
    # shim), plus continuous-vs-group batching p50 at low load.
    res = run_stage_subprocess(
        "fleet", 420, diagnostics, platform=serving_platform
    )
    if res and "error" in res:
        diagnostics.append({"fleet_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5a''''. Decoupled actor/learner (docs/RESILIENCE.md): lockstep vs
    # acting-through-the-serving-plane throughput at equal config, plus
    # the staleness distribution against --max-actor-lag, plus the
    # actor-fleet scaling curve (--actors {1,2,4} over the networked
    # staging transport). Host-side cost measurement like the serving
    # stages; same backend.
    res = run_stage_subprocess(
        "decoupled", 900, diagnostics, platform=serving_platform
    )
    if res and "error" in res:
        diagnostics.append({"decoupled_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5a'''''. Cold start (docs/SERVING.md "Cold start & warm-start
    # bundles"): time-to-first-act of a fresh serve.py worker with vs
    # without a warm-start bundle + pre-populated compile cache,
    # through the real operator CLI. Same backend as the serving
    # stages (the fingerprint pins bundle and worker to one platform).
    res = run_stage_subprocess(
        "coldstart", 600, diagnostics, platform=serving_platform
    )
    if res and "error" in res:
        diagnostics.append({"coldstart_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5b. Host env-loop throughput (pool on/off) — host-side CPU work
    # regardless of backend, so the child is pinned to the CPU platform
    # (no accelerator init). Subprocess + timeout: the wall-runner rows
    # build composer scenes for minutes, and a hung build must cost one
    # section, not the JSON line (same contract as the chip stages).
    res = run_stage_subprocess("host_envs", 900, diagnostics, platform="cpu")
    if res and "error" in res:
        diagnostics.append({"host_envs_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5c. Telemetry overhead (docs/OBSERVABILITY.md zero-overhead
    # contract): host-side instrumentation cost, measured where the
    # instrumentation lives — the host loop — so pinned to CPU like
    # the env section.
    res = run_stage_subprocess(
        "telemetry_overhead", 600, diagnostics, platform="cpu"
    )
    if res and "error" in res:
        diagnostics.append({"telemetry_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5c'. Run-wide observability overhead (obs/ collector + SLO
    # engine off vs on, same ABBA + 5% bar) — host-side like 5c.
    res = run_stage_subprocess(
        "obs_overhead", 600, diagnostics, platform="cpu"
    )
    if res and "error" in res:
        diagnostics.append({"obs_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5c''. Elastic vs fixed fleet over a diurnal load curve (the real
    # ElasticController on a simulated worker plane) — pure host-side
    # decision logic, CPU-pinned like the other instrumentation stages.
    res = run_stage_subprocess(
        "elastic", 300, diagnostics, platform="cpu"
    )
    if res and "error" in res:
        diagnostics.append({"elastic_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5d. Diagnostics-tier overhead (off/light/full ABBA; the
    # "light within 5%" acceptance bar of docs/OBSERVABILITY.md
    # "Learning-health diagnostics") — host+graph cost measured on the
    # CPU platform like the other instrumentation stages.
    res = run_stage_subprocess(
        "diagnostics_overhead", 720, diagnostics, platform="cpu"
    )
    if res and "error" in res:
        diagnostics.append({"diagnostics_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5e. Transfer-sanitizer overhead (--sanitize off vs on ABBA; the
    # off tier must be free, docs/ANALYSIS.md "Runtime sanitizers") —
    # host+dispatch cost, CPU-pinned like the other instrumentation
    # stages.
    res = run_stage_subprocess(
        "sanitize_overhead", 600, diagnostics, platform="cpu"
    )
    if res and "error" in res:
        diagnostics.append({"sanitize_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 5f. Tiered-replay throughput (docs/REPLAY.md): waterfall spill,
    # refill sampling, disk chunk IO and the --offline burst — host-
    # side costs like the other instrumentation stages, CPU-pinned.
    res = run_stage_subprocess(
        "replay", 600, diagnostics, platform="cpu"
    )
    if res and "error" in res:
        diagnostics.append({"replay_stage_error": res.pop("error")})
    if res:
        out.update(res)

    # 6. Torch-CPU baseline LAST; pinned fallback if it breaks.
    torch_sps, torch_keys = torch_baseline_metrics(diagnostics)
    out.update(torch_keys)

    if acc_sps is not None and torch_sps:
        out["vs_baseline"] = round(acc_sps / torch_sps, 2)

    # VERDICT r2 item 9: the on-device cheetah remains an honest
    # surrogate until MJX/Brax lands in the image (envs/ondevice.py
    # registry warning) — throughput numbers transfer, returns do not.
    out["notes"] = {
        "on_device_cheetah": (
            "surrogate dynamics (MJX/Brax not installed); host-loop "
            "path carries return parity (PARITY.md 1M-step gate)"
        )
    }

    if diagnostics:
        out["diagnostics"] = diagnostics
    if STAGE_ERRORS:
        # Structured per-stage failures (stage, elapsed, timeout,
        # partial output) — the artifact says WHICH stage died and how
        # far it got, not just an opaque merged string.
        out["stage_errors"] = list(STAGE_ERRORS)
    if out["value"] is None:
        out["error"] = "no accelerator benchmark completed"

    # 7. Chip-evidence persistence (VERDICT r2 item 1): a real-chip run
    # snapshots itself into runs/tpu/; a CPU fallback surfaces the
    # freshest prior chip snapshot so the recorded JSON always carries a
    # TPU-backed number once one has ever been measured.
    try:
        if out.get("backend") not in (None, "none", "cpu"):
            persist_tpu_artifact(out)
        else:
            lk = load_last_known_tpu()
            if lk:
                out["last_known_tpu"] = lk
                log(f"merged last-known chip artifact {lk.get('artifact')} "
                    f"(captured {lk.get('captured_utc')})")
    except Exception as e:  # noqa: BLE001 — evidence handling must not
        out.setdefault("diagnostics", []).append(  # cost the JSON line
            {"evidence_error": repr(e)}
        )

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1].startswith("--stage="):
        _run_stage_inprocess(sys.argv[1].split("=", 1)[1])
        sys.exit(0)
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — last-resort structured line
        print(json.dumps({
            "metric": "sac_grad_steps_per_sec", "value": None,
            "unit": "steps/sec", "vs_baseline": None,
            "error": f"fatal: {e!r}",
        }), flush=True)
    sys.exit(0)
