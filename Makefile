# Capability twin of the reference Makefile (ref Makefile:1-28): test
# runner plus operational helpers. The reference's mlflow/tensorboard/
# dvc/prefect UI stubs map to the file-based tracking under runs/.

.PHONY: test test-fast bench dryrun lint native clean

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -x --ignore=tests/test_wall_runner_env.py

bench:
	python bench.py

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		python __graft_entry__.py 8

lint:
	python -m flake8 torch_actor_critic_tpu tests || true

native:
	$(MAKE) -C torch_actor_critic_tpu/native

native-asan:
	$(MAKE) -C torch_actor_critic_tpu/native asan

clean:
	rm -rf runs __pycache__ **/__pycache__
