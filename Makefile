# Capability twin of the reference Makefile (ref Makefile:1-28): test
# runner plus operational helpers. The reference's mlflow/tensorboard/
# dvc/prefect UI stubs map to the file-based tracking under runs/.

.PHONY: test test-fast bench bench-diff dryrun lint native clean tpu-smoke tpu-watch parity multihost serve serve-smoke fault-smoke trace-smoke diag-smoke chaos-smoke pop-smoke cost-smoke mesh-smoke fleet-smoke shard-serve-smoke decouple-smoke visual-smoke scenario-smoke sanitize-smoke replay-smoke coldstart-smoke obs-smoke elastic-smoke

# Full matrix (CI runs this; ~14 min on a 2-thread host).
test:
	python -m pytest tests/ -q

# Iteration default: skips the @pytest.mark.slow tests (>30s each:
# multi-process launches, long training loops, native ASan build) and
# the composer wall-runner construction. <5 min.
test-fast:
	python -m pytest tests/ -q -x -m "not slow" --ignore=tests/test_wall_runner_env.py

bench:
	python bench.py

# Diff two bench artifacts; nonzero exit on a per-stage regression
# beyond the noise bar (A/B: raw bench JSON lines from runs/tpu/ or
# BENCH_rNN capture wrappers — truncated tails are partially
# recovered). See docs/OBSERVABILITY.md "Cost attribution & roofline".
A ?= BENCH_r04.json
B ?= BENCH_r05.json
bench-diff:
	python scripts/bench_diff.py $(A) $(B)

# Real-chip smoke: Pallas kernels fwd+bwd, fused burst, on-device env.
tpu-smoke:
	python scripts/tpu_smoke.py

# Poll the TPU tunnel and capture chip evidence into runs/tpu/ whenever
# it answers (leave running in the background for a whole session).
tpu-watch:
	bash scripts/tpu_watch.sh

# Return-parity runs vs the shared torch baseline (see PARITY.md).
parity:
	python scripts/parity_run.py --impl torch --env Pendulum-v1 \
		--steps 30000 --out runs_parity/torch_pendulum.jsonl
	python scripts/parity_run.py --impl jax --env Pendulum-v1 \
		--steps 30000 --out runs_parity/jax_pendulum.jsonl

# 2-process distributed dryrun (initialize_multihost, collective saves).
multihost:
	python -m pytest tests/test_multihost.py -q

# Serve a tracked run over HTTP (RUN=<id>; see docs/SERVING.md).
serve:
	python serve.py --run $(RUN)

# CI smoke: checkpoint -> serve.py CLI on a random port -> /act +
# /healthz round-trip; exits nonzero on failure.
serve-smoke:
	JAX_PLATFORMS=cpu python scripts/serve_smoke.py

# Observability smoke: tiny CPU run with telemetry + a --profile-epochs
# window; asserts the JSONL event stream, the XLA trace artifacts and
# the phase-coverage contract (docs/OBSERVABILITY.md).
trace-smoke:
	JAX_PLATFORMS=cpu python scripts/trace_smoke.py

# Learning-health diagnostics smoke: short full-tier CPU train;
# asserts every diagnostic key is present, finite and schema-valid in
# telemetry.jsonl/metrics.jsonl, the TD-error histogram merged, and
# the recompilation watchdog counting (docs/OBSERVABILITY.md
# "Learning-health diagnostics").
diag-smoke:
	JAX_PLATFORMS=cpu python scripts/diag_smoke.py

# Population-fused smoke: tiny CPU run of the vmapped Anakin loop
# (--on-device --population 4 --pbt-every 1) through the real CLI;
# asserts N distinct finite learning curves, at least one PBT exploit
# event with a schema-valid telemetry record, and a successful resume
# of the population checkpoint (docs/SCALING.md "population").
pop-smoke:
	JAX_PLATFORMS=cpu python scripts/pop_smoke.py

# Named-mesh GSPMD smoke: forced 4-device CPU run exercising the dp
# burst (jit-with-sharding, replica canary 0.0), the dp+fsdp hybrid
# (no version gate) and --population 8 member-sharded fused training
# end-to-end through the CLI, incl. a sharded-checkpoint resume
# (docs/SCALING.md "The mesh"). The script forces the device count
# itself before importing jax.
mesh-smoke:
	python scripts/mesh_smoke.py

# Compute-cost attribution smoke: short CPU train with telemetry + an
# in-process serve round -> every per-epoch `cost` event present and
# finite, serving /metrics carries per-bucket roofline entries, FLOPs
# monotone with bucket size, and one cross-plane Perfetto trace holds
# BOTH planes' spans (docs/OBSERVABILITY.md "Cost attribution").
cost-smoke:
	JAX_PLATFORMS=cpu python scripts/cost_smoke.py

# Fault-injection suite: every recovery path (NaN rollback, SIGTERM
# save+requeue+bitwise resume, checkpoint retry/fallback, dead env
# worker) driven through a real Trainer (docs/RESILIENCE.md).
fault-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q -m "not slow"

# Serving overload chaos: flood an in-process server past capacity
# with injected engine faults — queue stays bounded, breaker trips and
# recovers, NaN-checkpoint reload is rejected, drain answers every
# accepted request (docs/SERVING.md "Overload & degradation").
chaos-smoke:
	JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

# Fleet smoke: 3-worker CPU fleet through the real `serve.py --fleet`
# entry point — flood through the router, SIGKILL one worker MID-flood
# (membership ejects it, in-flight requests fail over, zero accepted
# drops), rolling /reload across the survivors, aggregated /metrics,
# graceful SIGTERM teardown (docs/SERVING.md "Fleet").
fleet-smoke:
	JAX_PLATFORMS=cpu python scripts/fleet_smoke.py

# Sharded-serving smoke: real `serve.py --devices all --submesh 2x2
# --fleet 2` under the forced 8-device CPU shim — each worker carves
# its devices into two (2,2) GSPMD sub-mesh replicas; flood the
# router, mid-flood validated hot-reload (one sharded transfer per
# replica, asserted via the transfer-bytes counter) and SIGKILL one
# worker: zero accepted drops, graceful SIGTERM teardown
# (docs/SERVING.md "Sharded serving & precision tiers").
shard-serve-smoke:
	JAX_PLATFORMS=cpu python scripts/shard_serve_smoke.py

# Decoupled actor/learner chaos: (1) in-process bitwise proof — SIGTERM
# mid-epoch with a staged-transition tail, resume is bitwise on learner
# state AND replay; (2) real processes — learner acts over HTTP through
# a serve.py worker hot-reloading its checkpoints, the worker is
# SIGKILLed mid-collection (actors degrade to the local snapshot, envs
# never stall), the learner SIGTERMs mid-epoch (requeue 75) and
# resumes: zero accepted transitions lost, staleness bounded by
# --max-actor-lag; (3) actor-process fleet — train.py --actors 3 over
# the networked staging transport with TAC_FLAKY_PUSH drops, one actor
# SIGKILLed (supervised restart + dead-actor purge), learner SIGTERM ->
# requeue 75 -> resume with restored dedup watermarks: the extended
# conservation invariant green, no push lost or double-ingested
# (docs/RESILIENCE.md "Decoupled-plane failure modes").
decouple-smoke:
	JAX_PLATFORMS=cpu python scripts/decouple_smoke.py

# Mixed-precision + fused-pixel-pipeline smoke (CPU, real CLI):
# Pallas pixel-kernel interpret-vs-reference bit parity, f32 fused
# pipeline bitwise vs the reference run, bf16 fused visual training
# finite, cost/epoch_mfu present in metrics.jsonl and cost events
# carrying the compute dtype (docs/SCALING.md "Mixed precision & the
# pixel pipeline").
visual-smoke:
	JAX_PLATFORMS=cpu python scripts/visual_smoke.py

# Transfer-sanitizer smoke (forced 4-device CPU, real CLIs): a short
# train and a 60-request serve flood both run CLEAN under --sanitize
# on (train loss stream bitwise == off), while an injected host read
# (numpy chunk into the guarded burst; numpy params into the guarded
# forward) trips jax.transfer_guard("disallow") loudly on each plane
# (docs/ANALYSIS.md "Runtime sanitizers"). The script forces the
# device count itself before importing jax.
sanitize-smoke:
	python scripts/sanitize_smoke.py

# Scenario-workloads smoke (CPU, real CLI): every scenarios/ pillar —
# multi-agent (per-agent reward curves), procedural (fresh level per
# episode, finite returns), multi-task (schema-valid per-task metrics
# from striped replay) — plus a bitwise population resume over the
# multi-task scenario (docs/SCENARIOS.md).
scenario-smoke:
	JAX_PLATFORMS=cpu python scripts/scenario_smoke.py

# Tiered-replay smoke (CPU, real CLI): --replay-tiers host is bitwise
# vs the tiers-off loss stream (and tiers-off emits zero replay/
# columns); a tiny-disk-budget run drives spill -> fifo evict ->
# refill -> prefetch with the conservation invariant holding every
# epoch; then --offline trains CQL-regularized SAC from the spilled
# chunks to a saved checkpoint (docs/REPLAY.md).
replay-smoke:
	JAX_PLATFORMS=cpu python scripts/replay_smoke.py

# Cold-start smoke (CPU, real CLI): build a warm-start bundle next to
# a real checkpoint (aot/), then prove against fresh serve.py workers
# that the bundle answers the first /act with ZERO serve-plane live
# compiles and holds zero through a closed-loop herd flood, that a
# second worker hits the shared persistent compile cache, and that a
# fingerprint-tampered bundle is loudly rejected with a counted
# fallback to live warmup (docs/SERVING.md "Cold start & warm-start
# bundles").
coldstart-smoke:
	JAX_PLATFORMS=cpu python scripts/coldstart_smoke.py

# Run-wide observability smoke (CPU, real CLI): a serving fleet
# (serve.py --fleet 2) plus an actor-fleet learner (--actors 2 --obs)
# whose ObsCollector aggregates three planes with zero scrape
# failures; an injected serving-goodput outage drives the SLO engine
# through exactly one breach + one recovery; and the exported Perfetto
# timeline stitches one staging span id across actor, transport, and
# learner process lanes (docs/OBSERVABILITY.md "Run-wide plane").
obs-smoke:
	JAX_PLATFORMS=cpu python scripts/obs_smoke.py

# Elastic self-healing fleet end-to-end (docs/RESILIENCE.md
# "Elasticity"): an SLO breach scales the serving fleet out from the
# warm pool, a worker SIGKILLed mid-spike is absorbed with ZERO
# dropped requests and a counted recovery, green windows drain one
# worker back in; on the training plane an actor SIGKILL degrades the
# run to the surviving slice (conservation green) and the slot is
# re-admitted at an epoch boundary — every decision a schema-valid
# event on the exported Perfetto elastic lane.
elastic-smoke:
	JAX_PLATFORMS=cpu python scripts/elastic_smoke.py

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		python __graft_entry__.py 8

# tac-lint: the codebase-native static pass (docs/ANALYSIS.md) —
# jit-hygiene, recompile-risk, lock-discipline, convention lints plus
# the dataflow families (donation-safety, prng-discipline,
# contract-drift). --json is the machine contract: one JSON object CI
# can diff, stable per-family exit codes (0 clean, 10..17 per family,
# 1 mixed). Also wired into tier-1 via tests/test_analysis.py's
# whole-package clean-run test.
lint:
	python -m torch_actor_critic_tpu.analysis --json torch_actor_critic_tpu scripts

native:
	$(MAKE) -C torch_actor_critic_tpu/native

native-asan:
	$(MAKE) -C torch_actor_critic_tpu/native asan

clean:
	rm -rf runs __pycache__ **/__pycache__
