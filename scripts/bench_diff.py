"""Diff two bench JSON artifacts and flag per-stage regressions.

    python scripts/bench_diff.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_diff.py A.json B.json --noise-pct 15

Walks both artifacts' numeric leaves (dotted paths: ``serving.p99_ms``,
``on_device.pendulum.env_steps_per_sec``, ``sweep[3].mfu``), compares
every performance-shaped key present in both, and prints the per-key
delta. Direction-aware: throughput-shaped keys (``*_per_sec``,
``*tflops``, ``mfu``, ``goodput``, the headline ``value``) regress when
they DROP; latency-shaped keys (``p50_ms``/``p99_ms``/``*_ms``) regress
when they RISE. Deltas within ``--noise-pct`` (default 10%) are noise.

Exit status: 0 = no regression beyond the noise bar, 1 = at least one
(CI-gateable: ``make bench-diff A=... B=...``), 2 = usage/IO error.
Keys that are not performance metrics (counters, geometry, static
FLOPs, notes) are ignored rather than producing false alarms.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

# Performance-shaped keys and their regression direction. Matched
# against the LEAF key name only (paths locate, names classify).
# mfu/cost-family keys are higher-is-better: `_mfu$` covers both the
# bench stages' `mfu`/`est_mfu` and the metrics.jsonl roofline columns
# (`cost/epoch_mfu`, `cost/update_burst_mfu` — the leaf name keeps its
# `cost/` prefix, the suffix classifies); `gflops_s$` covers the
# achieved-FLOP/s columns (`cost/*_achieved_gflops_s`). An MFU drop
# now regresses `make bench-diff` exactly like a goodput drop.
HIGHER_BETTER = re.compile(
    r"(per_sec|_rps$|tflops|^mfu$|_mfu$|^est_mfu$|goodput|occupancy"
    r"|^value$|^value_bf16$|scaling_vs_1|roofline_frac|gflops_s$"
    # Cold-start stage (bench_coldstart): bundle speedup and the
    # persistent-compile-cache hit rate; its *_ms keys (ready_ms /
    # first_act_ms) already ride the lower-better _ms$ direction.
    r"|hbm_util$|_speedup$|hit_rate$"
    # Run-wide obs plane (obs/; bench_obs_overhead + the obs/ metric
    # columns): live scrape sources are goodput for the collector.
    r"|sources_live$)"
)
LOWER_BETTER = re.compile(
    r"(^p50_ms$|^p95_ms$|^p99_ms$|^mean_ms$|^max_ms$|_ms$"
    r"|^ms_per_lockstep_round$|overhead.*_pct$"
    # Obs plane: failed scrapes and SLO breaches regress the run even
    # when throughput holds (the collector itself must stay healthy).
    r"|_failed_total$|breaches_total$"
    # Elastic plane (bench --stage=elastic): worker-seconds is the
    # cost axis the autoscaler trades against goodput/p99 — paying
    # more of it for the same curve is a regression. Shed requests
    # regress goodput even when the served rate holds.
    r"|worker_seconds$|shed_total$)"
)


def load_artifact(path: str):
    """Load a bench artifact: either a raw bench JSON line (runs/tpu/
    bench_*.json) or a BENCH_rNN capture wrapper whose ``tail`` holds
    the (possibly front-truncated) stdout line. Truncated tails are
    recovered from the first top-level ``, "key":`` resync point —
    the trailing sections (serving, visual, headline value...) survive
    even when the line's start was cut. Returns ``(record, partial)``.
    """
    with open(path) as f:
        data = json.load(f)
    if "metric" in data or "tail" not in data:
        return data, False
    tail = data["tail"]
    start = tail.find('{"metric')
    if start >= 0:
        try:
            return json.loads(tail[start:]), False
        except json.JSONDecodeError:
            pass
    for m in re.finditer(r', "', tail):
        cand = "{" + tail[m.start() + 2:]
        # A tail cut inside a NESTED section leaves unmatched trailing
        # braces; peeling up to three recovers resync points one or two
        # levels deep (e.g. a tail entirely inside `last_known_tpu`).
        for strip in range(4):
            try:
                rec = json.loads(cand[:len(cand) - strip or None])
            except json.JSONDecodeError:
                continue
            # Leftmost resync wins: a successful parse must consume to
            # the (peeled) end of the line, so earlier points recover a
            # superset of later ones.
            if isinstance(rec, dict) and rec:
                return rec, True
            break
    raise ValueError(
        f"{path}: neither a bench JSON line nor a recoverable capture "
        "wrapper"
    )


def numeric_leaves(node, path=""):
    """Yield (dotted_path, leaf_key, value) for every numeric leaf."""
    if isinstance(node, dict):
        for k, v in node.items():
            yield from numeric_leaves(v, f"{path}.{k}" if path else str(k))
    elif isinstance(node, list):
        for i, v in enumerate(node):
            yield from numeric_leaves(v, f"{path}[{i}]")
    elif isinstance(node, (int, float)) and not isinstance(node, bool):
        leaf = path.rsplit(".", 1)[-1]
        yield path, leaf, float(node)


def compare(a: dict, b: dict, noise_pct: float):
    """(rows, regressions): every compared key with its delta, and the
    subset regressing beyond the noise bar."""
    a_leaves = {p: (k, v) for p, k, v in numeric_leaves(a)}
    rows, regressions = [], []
    for path, leaf, vb in sorted(numeric_leaves(b)):
        if path not in a_leaves:
            continue
        if HIGHER_BETTER.search(leaf):
            direction = +1
        elif LOWER_BETTER.search(leaf):
            direction = -1
        else:
            continue
        va = a_leaves[path][1]
        if va == 0:
            continue
        delta_pct = (vb - va) / abs(va) * 100.0
        # A drop in a higher-better key (or a rise in a lower-better
        # one) beyond the noise bar is a regression.
        regressed = (-direction * delta_pct) > noise_pct
        rows.append((path, va, vb, delta_pct, direction, regressed))
        if regressed:
            regressions.append(rows[-1])
    return rows, regressions


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Diff two bench JSON artifacts; nonzero exit on "
        "regression beyond the noise bar."
    )
    p.add_argument("artifact_a", help="older bench JSON (the baseline)")
    p.add_argument("artifact_b", help="newer bench JSON (the candidate)")
    p.add_argument(
        "--noise-pct", type=float, default=10.0,
        help="deltas within this band are noise, not regressions "
        "(default 10)",
    )
    p.add_argument(
        "--all", action="store_true",
        help="print every compared key, not just the regressions",
    )
    args = p.parse_args(argv)

    try:
        a, a_partial = load_artifact(args.artifact_a)
        b, b_partial = load_artifact(args.artifact_b)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"bench-diff: cannot load artifacts: {e}", file=sys.stderr)
        return 2
    for name, partial in (
        (args.artifact_a, a_partial), (args.artifact_b, b_partial),
    ):
        if partial:
            print(
                f"bench-diff: note: {name} is a truncated capture "
                "wrapper; only its recovered trailing sections are "
                "compared", file=sys.stderr,
            )

    rows, regressions = compare(a, b, args.noise_pct)
    if not rows:
        print("bench-diff: no comparable performance keys found")
        return 2

    width = max(len(r[0]) for r in rows)
    print(
        f"bench-diff: {args.artifact_a} -> {args.artifact_b} "
        f"({len(rows)} keys, noise bar {args.noise_pct:g}%)"
    )
    print(f"{'key':<{width}}  {'A':>12}  {'B':>12}  {'delta':>8}")
    shown = rows if args.all else [
        r for r in rows if r[5] or abs(r[3]) > args.noise_pct
    ]
    for path, va, vb, delta, direction, regressed in shown:
        flag = "REGRESSION" if regressed else (
            "improved" if (direction * delta) > args.noise_pct else ""
        )
        print(
            f"{path:<{width}}  {va:>12.4g}  {vb:>12.4g}  "
            f"{delta:>+7.1f}%  {flag}"
        )
    if not shown:
        print(f"(all {len(rows)} compared keys within the noise bar)")
    if regressions:
        print(
            f"bench-diff: {len(regressions)} regression(s) beyond "
            f"{args.noise_pct:g}%", file=sys.stderr,
        )
        return 1
    print("bench-diff: no regressions beyond the noise bar")
    return 0


if __name__ == "__main__":
    sys.exit(main())
