"""End-to-end smoke of the tiered replay store + data flywheel.

Drives the whole docs/REPLAY.md surface through the REAL CLI entry
point (``train.py``), asserting the contracts the subsystem promises:

1. **Bitwise HBM tier** — a run with ``--replay-tiers host`` produces
   the exact same per-epoch loss stream as the tiers-off run at the
   same seed (tier 0 is today's device ring, bit for bit; the shadow
   accounting never touches the jit path), and the tiers-off run emits
   ZERO ``replay/`` metric columns (default-off means invisible).
2. **Spill → evict → refill → prefetch** — a run with the disk tier, a
   tiny disk budget (forces fifo eviction) and ``--replay-refill`` on:
   finite losses, chunks + manifest on disk, evictions counted, refills
   served with prefetch hits, and the per-tier conservation invariant
   (``replay/conservation_ok``) holding on every epoch.
3. **Offline training from the spilled dataset** — ``train.py
   --offline`` pointed at the disk tier run (2) just wrote trains CQL-
   regularized SAC end-to-end with finite losses and a saved final
   checkpoint.

The ``make replay-smoke`` gate; ~90s on a 2-thread CPU host.
"""

import json
import math
import os
import sys
import tempfile
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The loss columns the bitwise A/B comparison pins.
LOSS_KEYS = ("loss_q", "loss_pi", "avg_return")

TINY = [
    "--environment", "Pendulum-v1",
    "--devices", "1",
    "--seed", "0",
    "--epochs", "3",
    "--steps-per-epoch", "120",
    "--start-steps", "30",
    "--update-after", "30",
    "--update-every", "10",
    "--batch-size", "16",
    "--buffer-size", "200",
    "--hidden-sizes", "16,16",
    "--max-ep-len", "100",
]


def fail(msg):
    print(f"[replay-smoke] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def run(train_main, root, extra):
    train_main(TINY + ["--runs-root", str(root)] + extra)
    run_dir = next((Path(root) / "Default").iterdir())
    rows = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    if not rows:
        fail(f"no metrics rows under {run_dir}")
    return run_dir, rows


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from torch_actor_critic_tpu.train import main as train_main

    scratch = Path(tempfile.mkdtemp(prefix="replay_smoke_"))

    # ---- 1. bitwise HBM tier: off vs host-tier shadow ----------------
    _, rows_off = run(train_main, scratch / "a_off", [])
    _, rows_host = run(
        train_main, scratch / "b_host", ["--replay-tiers", "host"]
    )
    if any(k.startswith("replay/") for r in rows_off for k in r):
        fail("tiers-off run leaked replay/ metric columns")
    if len(rows_off) != len(rows_host):
        fail(f"epoch counts differ: {len(rows_off)} vs {len(rows_host)}")
    for ra, rb in zip(rows_off, rows_host):
        for key in LOSS_KEYS:
            if ra.get(key) != rb.get(key):
                fail(
                    f"loss stream diverged at step {ra.get('step')}: "
                    f"{key} {ra.get(key)!r} (off) vs {rb.get(key)!r} (host)"
                )
    for r in rows_host:
        if r.get("replay/conservation_ok") != 1.0:
            fail(f"host-tier conservation broken: {r}")
        if "replay/hbm_bytes" not in r or r["replay/hbm_bytes"] <= 0:
            fail("replay/hbm_bytes missing or non-positive")
    print(
        f"[replay-smoke] bitwise ok: {len(rows_off)} epochs, loss "
        "stream identical off vs host tier; conservation holds"
    )

    # ---- 2. spill -> evict -> refill -> prefetch through the CLI ----
    replay_dir = scratch / "disk_tier"
    _, rows_disk = run(train_main, scratch / "c_disk", [
        "--replay-tiers", "disk",
        "--replay-dir", str(replay_dir),
        "--replay-host-capacity", "120",
        "--replay-disk-bytes", "8192",    # a few chunks: forces fifo evict
        "--replay-refill", "2",
        "--replay-prefetch", "true",
    ])
    last = rows_disk[-1]
    for key in LOSS_KEYS[:2]:
        v = last.get(key)
        if v is None or not math.isfinite(float(v)):
            fail(f"disk-tier run non-finite {key}: {v!r}")
    for r in rows_disk:
        if r.get("replay/conservation_ok") != 1.0:
            fail(f"disk-tier conservation broken: {r}")
    if last.get("replay/spilled_disk_total", 0) <= 0:
        fail(f"no rows spilled to disk: {last}")
    if last.get("replay/disk_evicted_rows_total", 0) <= 0:
        fail(f"disk budget never evicted: {last}")
    if last.get("replay/refills_served", 0) <= 0:
        fail(f"no refills served: {last}")
    if last.get("replay/prefetch_hit_rate", 0) <= 0:
        fail(f"prefetch never hit: {last}")
    chunks = sorted(replay_dir.glob("chunk-*.npz"))
    if not chunks or not (replay_dir / "manifest.jsonl").exists():
        fail(f"disk tier artifacts missing under {replay_dir}")
    meta = json.loads((replay_dir / "meta.json").read_text())
    if meta.get("act_dim") != 1 or "obs" not in meta:
        fail(f"disk tier meta malformed: {meta}")
    print(
        f"[replay-smoke] tier flow ok: spilled "
        f"{last['replay/spilled_disk_total']:.0f} rows, evicted "
        f"{last['replay/disk_evicted_rows_total']:.0f}, "
        f"{last['replay/refills_served']:.0f} refills (hit rate "
        f"{last['replay/prefetch_hit_rate']:.2f}), "
        f"{len(chunks)} chunks resident"
    )

    # ---- 3. --offline from the dataset run (2) just spilled ----------
    off_root = scratch / "d_offline"
    train_main([
        "--runs-root", str(off_root),
        "--hidden-sizes", "16,16",
        "--batch-size", "16",
        "--offline", "true",
        "--offline-dataset", str(replay_dir),
        "--offline-steps", "60",
        "--offline-reg", "cql",
        "--offline-reg-weight", "0.5",
        "--seed", "0",
    ])
    off_dir = next((off_root / "Default").iterdir())
    off_rows = [
        json.loads(line)
        for line in (off_dir / "metrics.jsonl").read_text().splitlines()
    ]
    if not off_rows:
        fail("offline run wrote no metrics")
    final = off_rows[-1]
    for key in ("loss_q", "loss_pi", "offline/cql_gap"):
        v = final.get(key)
        if v is None or not math.isfinite(float(v)):
            fail(f"offline non-finite {key}: {v!r}")
    if final.get("offline/steps") != 60.0:
        fail(f"offline step count wrong: {final.get('offline/steps')}")
    ckpts = list((off_dir / "artifacts" / "checkpoints").glob("*"))
    if not ckpts:
        fail(f"offline run saved no checkpoint under {off_dir}")
    print(
        f"[replay-smoke] offline ok: 60 CQL steps from "
        f"{final['offline/dataset_rows']:.0f} spilled rows, "
        f"loss_q={final['loss_q']:.3f}, cql_gap="
        f"{final['offline/cql_gap']:.3f}, checkpoint saved"
    )
    print("[replay-smoke] PASS")


if __name__ == "__main__":
    main()
