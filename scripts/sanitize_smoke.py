"""End-to-end smoke of the --sanitize transfer-guard tier (docs/ANALYSIS.md).

Forces a 4-device CPU backend (the tier-1 shim) and proves the runtime
sanitizer's contract on both planes, through the real entry points:

- **train, clean**: a short ``train.py --sanitize on`` run on the dp=4
  mesh completes with finite losses, and its loss stream is BITWISE
  equal to the same seed with ``--sanitize off`` — the guard is
  behavior-neutral on a clean path (the off tier's no-op parity is
  pinned the other way round by tests/test_sanitize.py);
- **train, trip**: an injected host read — the placed chunk left as
  raw numpy so the guarded burst dispatch sees an implicit
  host->device transfer — fails the epoch loudly with the guard's
  XlaRuntimeError instead of silently taxing every window;
- **serve, clean**: a real ``serve.py --sanitize on`` subprocess
  floods 60 ``/act`` requests (deterministic and sampled) over
  loopback — every one answered, none tripped, proving the explicit
  ``device_put`` staging covers the whole request path;
- **serve, trip**: an engine handed host-numpy params under sanitize
  raises at the first forward (the per-request re-transfer tax the
  tier exists to catch).

The ``make sanitize-smoke`` gate; ~2 min on a 2-thread CPU host.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
from urllib import request as urlreq

# Must precede the first jax import anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_DEV = 4
OBS_DIM, ACT_DIM = 6, 2
FLOOD = 60

TINY = dict(
    hidden_sizes=(16, 16), batch_size=16, epochs=2, steps_per_epoch=120,
    start_steps=30, update_after=30, update_every=30, buffer_size=2000,
    max_ep_len=100, save_every=1000, sentinel=False,
)


def fail(msg, proc=None):
    print(f"[sanitize-smoke] FAIL: {msg}", file=sys.stderr)
    if proc is not None:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=10)
            print(out[-3000:], file=sys.stderr)
        except subprocess.TimeoutExpired:
            proc.kill()
    sys.exit(1)


def ok(msg):
    print(f"[sanitize-smoke] {msg}", flush=True)


def check_train_clean_and_parity():
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig

    import numpy as np

    metrics = {}
    for tier in ("off", "on"):
        tr = Trainer(
            "Pendulum-v1", SACConfig(**TINY, sanitize=tier),
            mesh=make_mesh(dp=N_DEV), seed=7,
        )
        try:
            metrics[tier] = tr.train()
        finally:
            tr.close()
    for k in ("loss_q", "loss_pi", "reward"):
        a, b = metrics["off"][k], metrics["on"][k]
        if not np.isfinite(b):
            fail(f"sanitize=on {k} not finite: {b}")
        if a != b:
            fail(f"sanitize on/off diverged on {k}: {a} != {b}")
    if set(metrics["off"]) != set(metrics["on"]):
        fail("sanitize tier changed the metric schema")
    ok(
        f"dp={N_DEV} train under sanitize=on: clean, loss stream "
        f"bitwise == off (loss_q={metrics['on']['loss_q']:.4f})"
    )


def check_train_trip():
    import torch_actor_critic_tpu.sac.trainer as trmod
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig

    tr = Trainer(
        "Pendulum-v1", SACConfig(**TINY, sanitize="on"),
        mesh=make_mesh(dp=1), seed=7,
    )
    orig = trmod.shard_chunk_from_local
    # The injected host read: leave the window's chunk as raw numpy so
    # the guarded burst dispatch must transfer implicitly.
    trmod.shard_chunk_from_local = lambda chunk, mesh, sp=1: chunk
    try:
        tr.train()
        fail("guarded burst accepted a host-resident chunk")
    except Exception as e:  # noqa: BLE001 — asserting the trip class
        if "transfer" not in repr(e).lower():
            fail(f"expected a transfer-guard trip, got {e!r}")
        ok(f"injected host read tripped the guard: {type(e).__name__}")
    finally:
        trmod.shard_chunk_from_local = orig
        tr.close()


def check_serve_flood():
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    tmp = tempfile.mkdtemp(prefix="sanitize_smoke_")
    ckpt_dir = os.path.join(tmp, "ckpts")
    cfg = SACConfig(hidden_sizes=(16, 16))
    sac = SAC(
        cfg,
        Actor(act_dim=ACT_DIM, hidden_sizes=(16, 16)),
        DoubleCritic(hidden_sizes=(16, 16)),
        ACT_DIM,
    )
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    ck = Checkpointer(ckpt_dir, save_buffer=False)
    ck.save(0, state, extra={"config": cfg.to_json()}, wait=True)
    ck.close()

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + (
            os.pathsep + os.environ["PYTHONPATH"]
            if os.environ.get("PYTHONPATH") else ""
        ),
        PALLAS_AXON_POOL_IPS="",
    )
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "serve.py"),
            "--ckpt-dir", ckpt_dir,
            "--obs-dim", str(OBS_DIM), "--act-dim", str(ACT_DIM),
            "--port", "0", "--max-batch", "8", "--max-wait-ms", "2",
            "--sanitize", "on",
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )
    address, deadline = None, time.time() + 180
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                fail(f"server exited rc={proc.returncode} before ready", proc)
            time.sleep(0.1)
            continue
        if line.startswith("{"):
            try:
                address = json.loads(line)["serving"]
                break
            except (json.JSONDecodeError, KeyError):
                continue
    if address is None:
        fail("server never printed its address", proc)
    ok(f"sanitized server up at {address}")
    try:
        answered = 0
        for i in range(FLOOD):
            obs = [0.01 * (i + j) for j in range(OBS_DIM)]
            req = urlreq.Request(
                address + "/act",
                data=json.dumps(
                    {"obs": obs, "deterministic": i % 2 == 0}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            out = json.loads(urlreq.urlopen(req, timeout=30).read())
            if len(out["action"]) != ACT_DIM:
                fail(f"bad action on request {i}: {out}", proc)
            answered += 1
        if answered != FLOOD:
            fail(f"only {answered}/{FLOOD} answered", proc)
        ok(
            f"{FLOOD}/{FLOOD} /act requests (det + sampled) answered "
            "under the transfer guard"
        )
    except Exception as e:  # noqa: BLE001 — any failure is a smoke fail
        fail(repr(e), proc)
    finally:
        proc.terminate()
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def check_serve_trip():
    import jax
    import numpy as np

    from torch_actor_critic_tpu.models import Actor
    from torch_actor_critic_tpu.serve.engine import PolicyEngine

    actor = Actor(act_dim=ACT_DIM, hidden_sizes=(16, 16))
    spec = jax.ShapeDtypeStruct((OBS_DIM,), np.float32)
    params = actor.init(
        jax.random.key(0), np.zeros((1, OBS_DIM), np.float32), None,
        deterministic=True, with_logprob=False,
    )
    engine = PolicyEngine(actor, spec, max_batch=4, sanitize=True)
    np_params = jax.tree_util.tree_map(np.asarray, params)
    try:
        engine.act(
            np_params, np.zeros((2, OBS_DIM), np.float32),
            deterministic=True,
        )
        fail("sanitized engine accepted host-numpy params")
    except Exception as e:  # noqa: BLE001 — asserting the trip class
        if "transfer" not in repr(e).lower():
            fail(f"expected a transfer-guard trip, got {e!r}")
        ok(f"host-numpy params tripped the guard: {type(e).__name__}")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.device_count() != N_DEV:
        fail(
            f"expected {N_DEV} forced CPU devices, got "
            f"{jax.device_count()} (XLA_FLAGS not honored)"
        )
    check_train_clean_and_parity()
    check_train_trip()
    check_serve_flood()
    check_serve_trip()
    ok("OK")


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    main()
