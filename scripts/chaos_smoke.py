"""Chaos smoke for the serving plane (make chaos-smoke, CPU, ~1 min).

Drives the three overload-containment claims of docs/SERVING.md
"Overload & degradation" through a REAL in-process server — the same
registry/batcher/engine stack production runs, with faults injected by
resilience/faultinject.py:

1. **Flood past capacity**: a closed-loop client herd floods the
   batcher at well past service rate (the engine is slowed to make
   CPU forwards the bottleneck). Asserts the queue NEVER exceeds its
   configured bound, every ACCEPTED request is answered, and every
   rejected submit carried a structured reason + retry hint.
2. **Breaker trip + recovery**: NaN params (in-graph finiteness check)
   trip the slot breaker; requests fail fast while open; after the
   cooldown a half-open probe against restored-good params closes it
   and traffic resumes.
3. **Validated reload**: a NaN-corrupted checkpoint epoch is rejected
   by the all-finite sentinel — the slot keeps serving its last-good
   generation — and a clean drain answers everything still queued.

Exits nonzero on any violated invariant; prints a one-line JSON
summary for CI logs.
"""

import json
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.resilience.faultinject import (
        corrupt_checkpoint,
        flood,
        nan_params,
    )
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.serve import (
        BreakerOpenError,
        CircuitBreaker,
        MicroBatcher,
        ModelRegistry,
        NonFiniteActionError,
        ShedError,
    )
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    OBS_DIM, ACT_DIM = 17, 6
    CAPACITY = 16
    obs = np.ones((OBS_DIM,), np.float32)
    summary = {}

    actor = Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32))
    good_params = actor.init(
        jax.random.key(0), jnp.zeros((OBS_DIM,)), jax.random.key(1)
    )
    spec = jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32)
    breaker = CircuitBreaker(fail_threshold=3, cooldown_s=0.3)
    reg = ModelRegistry()
    reg.register(
        "default", actor, spec, params=good_params, max_batch=8,
        breaker=breaker,
    )

    # Slow the engine so a tiny CPU flood is a REAL overload (service
    # rate ~ max_batch / 5ms) without needing thousands of threads.
    engine, _, _ = reg.acquire("default")
    real_act = engine.act

    def slow_act(*args, **kwargs):
        time.sleep(0.005)
        return real_act(*args, **kwargs)

    engine.act = slow_act

    with MicroBatcher(
        reg, max_batch=8, max_wait_ms=1.0, capacity=CAPACITY
    ) as mb:
        # ---------------------------------------------- 1. flood
        depth_samples = []
        stop_sampler = threading.Event()

        def sampler():
            while not stop_sampler.is_set():
                depth_samples.append(mb.queue_depth())
                time.sleep(0.002)

        smp = threading.Thread(target=sampler, daemon=True)
        smp.start()
        futures, sheds = [], []
        flood_lock = threading.Lock()

        def flooder():
            f, s = flood(mb.submit, obs, 200)
            with flood_lock:
                futures.extend(f)
                sheds.extend(s)

        herd = [threading.Thread(target=flooder) for _ in range(8)]
        t0 = time.perf_counter()
        for th in herd:
            th.start()
        for th in herd:
            th.join(timeout=120.0)
        answered = 0
        for f in futures:
            res = f.result(timeout=120.0)  # raises if dropped/errored
            assert res.action.shape == (ACT_DIM,)
            answered += 1
        flood_s = time.perf_counter() - t0
        stop_sampler.set()
        smp.join(timeout=10.0)
        offered = len(futures) + len(sheds)
        assert offered == 8 * 200, offered
        assert len(sheds) > 0, "flood never exceeded capacity"
        assert all(e.reason == "queue_full" for e in sheds)
        assert all(e.retry_after_s > 0 for e in sheds)
        max_depth = max(depth_samples) if depth_samples else 0
        assert max_depth <= CAPACITY, (
            f"queue depth {max_depth} exceeded bound {CAPACITY}"
        )
        summary["flood"] = {
            "offered": offered,
            "accepted_and_answered": answered,
            "shed": len(sheds),
            "max_queue_depth": max_depth,
            "capacity": CAPACITY,
            "goodput_rps": round(answered / flood_s, 1),
        }

        # --------------------------------------- 2. breaker cycle
        reg.swap("default", nan_params(good_params), validate=False)
        failures = 0
        while breaker.state != "open":
            assert failures < 50, "breaker never tripped"
            try:
                mb.act(obs, timeout=30.0)
            except NonFiniteActionError:
                failures += 1
            except BreakerOpenError:
                break
        assert breaker.trips_total >= 1
        # open -> fail fast, zero engine work
        try:
            mb.act(obs, timeout=30.0)
            raise AssertionError("open breaker admitted a request")
        except (BreakerOpenError, NonFiniteActionError):
            pass
        # heal the engine, wait out the cooldown, probe recovers
        reg.swap("default", good_params)
        deadline = time.time() + 30.0
        while True:
            assert time.time() < deadline, "breaker never recovered"
            try:
                res = mb.act(obs, timeout=30.0)
                break
            except (BreakerOpenError, NonFiniteActionError):
                time.sleep(0.05)
        assert breaker.state == "closed"
        assert res.action.shape == (ACT_DIM,)
        summary["breaker"] = {
            "failures_to_trip": failures,
            "trips_total": breaker.trips_total,
            "probes_total": breaker.probes_total,
            "final_state": breaker.state,
            "events": len(reg.breaker_events()),
        }

        # ------------------------------- 3. validated hot-reload
        with tempfile.TemporaryDirectory() as tmp:
            cfg = SACConfig(hidden_sizes=(32, 32))
            sac = SAC(
                cfg, Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32)),
                DoubleCritic(hidden_sizes=(32, 32)), ACT_DIM,
            )
            ck = Checkpointer(tmp, save_buffer=False)
            ck.save(
                0, sac.init_state(jax.random.key(2), jnp.zeros((OBS_DIM,))),
                extra={"config": cfg.to_json()}, wait=True,
            )
            ck.close()
            reg.register(
                "reloadable", Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32)),
                spec, ckpt_dir=str(tmp), max_batch=8, warmup=False,
            )
            # The trainer then "writes" a NaN-poisoned epoch 1 — a
            # structurally valid checkpoint only the sentinel can
            # catch. Reload must reject it and keep the last-good
            # generation serving.
            ck = Checkpointer(tmp, save_buffer=False)
            ck.save(
                1, sac.init_state(jax.random.key(3), jnp.zeros((OBS_DIM,))),
                extra={"config": cfg.to_json()}, wait=True,
            )
            ck.close()
            corrupt_checkpoint(tmp, 1, mode="nan-params")
            before_gen = reg.slots()["reloadable"]["generation"]
            out = reg.reload("reloadable")
            assert out["reloadable"]["status"] == "rejected", out
            assert out["reloadable"]["reloaded"] is False
            assert reg.slots()["reloadable"]["generation"] == before_gen
            res = mb.act(obs, slot="reloadable", timeout=30.0)
            assert np.isfinite(res.action).all()
            summary["reload"] = {
                "status": out["reloadable"]["status"],
                "generation_unchanged": True,
            }

        # ---------------------------------------------- 4. drain
        tail = [mb.submit(obs) for _ in range(CAPACITY // 2)]
        mb.close()  # stop admissions + flush: the drain core
        for f in tail:
            assert f.result(timeout=30.0).action.shape == (ACT_DIM,)
        try:
            mb.submit(obs)
            raise AssertionError("closed batcher accepted a request")
        except ShedError as e:
            assert e.reason == "draining"
        snap = mb.metrics.snapshot()
        summary["drain"] = {
            "flushed": len(tail),
            "responses_total": snap["responses_total"],
            "sheds_total": snap["sheds_total"],
        }

    reg.close()
    print("CHAOS-SMOKE OK " + json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
