"""Regenerate the committed evidence runs that PARITY.md cites.

Round 3 quoted bf16 / sequence / TD3 / wall-runner results whose run
directories were lost to the ``runs/*`` gitignore (only ``runs/tpu/``
was whitelisted).  This script re-runs each cited configuration as a
named preset and writes its artifacts to ``runs/<preset>/<run_id>/``
(metrics.jsonl + params.json + summary.json), which .gitignore now
whitelists so every number in PARITY.md maps to a tracked file.

Usage::

    JAX_PLATFORMS=cpu python scripts/evidence_run.py bf16flat
    python scripts/evidence_run.py --list

Each preset is the exact configuration PARITY.md describes (the torch
side of those comparisons lives in ``runs_parity/`` and is unchanged).
The summary line records deterministic-eval stats over 10 episodes —
the reference's eval protocol (ref ``run_agent.py:19-48``) — plus wall
time, so the regenerated numbers supersede the round-3 quotes.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from torch_actor_critic_tpu.sac.ondevice import PIXEL_CONV, PIXEL_RECIPE


def _preset(env, seed=0, eval_episodes=10, **overrides):
    return {"env": env, "seed": seed, "eval_episodes": eval_episodes,
            "overrides": overrides}


# Step budgets follow PARITY.md's quoted configurations: 16k-step
# Pendulum for the bf16/sequence points, the reference HalfCheetah
# budgets (100k/300k/1M) for the algorithm-level numbers, and the
# round-3 wall-runner epoch geometry.
PRESETS = {
    # bf16 learning preservation, flat MLP (PARITY.md "Mixed precision")
    "bf16flat": _preset(
        "Pendulum-v1", epochs=4, steps_per_epoch=4000, max_ep_len=1000,
        buffer_size=16_000, compute_dtype="bfloat16",
    ),
    # bf16 through the history-8 causal transformer
    "bf16seq": _preset(
        "Pendulum-v1", epochs=4, steps_per_epoch=4000, max_ep_len=1000,
        buffer_size=16_000, compute_dtype="bfloat16",
        history_len=8, seq_d_model=48, seq_num_layers=1,
    ),
    # f32 sequence-policy convergence (PARITY.md "Sequence-policy
    # convergence")
    "seqparity": _preset(
        "Pendulum-v1", epochs=4, steps_per_epoch=4000, max_ep_len=1000,
        buffer_size=16_000,
        history_len=8, seq_d_model=48, seq_num_layers=1,
    ),
    # bf16 at the full HalfCheetah parity budget
    "bf16cheetah": _preset(
        "HalfCheetah-v5", epochs=20, steps_per_epoch=5000, max_ep_len=1000,
        buffer_size=100_000, compute_dtype="bfloat16",
    ),
    # TD3 at the reference budgets (--algorithm td3 with the TD3
    # paper's warmup: 10k random-action steps, updates from 1k — the
    # round-3 configuration; Fujimoto et al. 2018 table 3).
    "td3cheetah100k": _preset(
        "HalfCheetah-v5", epochs=20, steps_per_epoch=5000, max_ep_len=1000,
        buffer_size=100_000, algorithm="td3",
        start_steps=10_000, update_after=1000,
    ),
    "td3cheetah100k-s1": _preset(
        "HalfCheetah-v5", seed=1, epochs=20, steps_per_epoch=5000,
        max_ep_len=1000, buffer_size=100_000, algorithm="td3",
        start_steps=10_000, update_after=1000,
    ),
    "td3cheetah300k": _preset(
        "HalfCheetah-v5", epochs=60, steps_per_epoch=5000, max_ep_len=1000,
        buffer_size=300_000, algorithm="td3",
        start_steps=10_000, update_after=1000,
    ),
    "td3cheetah1M": _preset(
        "HalfCheetah-v5", epochs=200, steps_per_epoch=5000, max_ep_len=1000,
        buffer_size=1_000_000, algorithm="td3",
        start_steps=10_000, update_after=1000,
    ),
    "td3cheetah1M-s1": _preset(
        "HalfCheetah-v5", seed=1, epochs=200, steps_per_epoch=5000,
        max_ep_len=1000, buffer_size=1_000_000, algorithm="td3",
        start_steps=10_000, update_after=1000,
    ),
    # Pixel-learning proof (VERDICT r3 #1): visual SAC on the honest
    # pixel task, at the reference's scalar-vision parity bottleneck
    # (cnn_features=1, unnormalized uint8 — ref convolutional.py:46-49)
    # and at the widened extension. Conv geometry sized for the 32x32
    # frames the same way the Atari defaults size 64x64.
    "pixelpend-parity": _preset(
        "PixelPendulum-v0", epochs=5, steps_per_epoch=4000, max_ep_len=1000,
        buffer_size=32_000,
        filters=(16, 32), kernel_sizes=(4, 3), strides=(2, 2),
        cnn_dense_size=128, cnn_features=1, normalize_pixels=False,
    ),
    # Widened extension run with the framework's pixel-RL recipe:
    # DrQ random-shift augmentation + learned temperature (vanilla
    # pixel SAC is the known-unstable baseline — the pixelpend-vanilla
    # control records it).
    "pixelpend-wide": _preset(
        "PixelPendulum-v0", epochs=8, steps_per_epoch=4000, max_ep_len=1000,
        buffer_size=32_000,
        **PIXEL_RECIPE,
    ),
    # Vanilla control: widened vision, NO augmentation, fixed alpha —
    # isolates what the DrQ recipe adds.
    "pixelpend-vanilla": _preset(
        "PixelPendulum-v0", epochs=5, steps_per_epoch=4000, max_ep_len=1000,
        buffer_size=32_000,
        **PIXEL_CONV,
    ),
    # Balance-start pixel task (stabilization, not swing-up
    # discovery): the learning signal is reachable within a CPU-budget
    # run, so this trio carries the committed learning-curve proof —
    # DrQ recipe vs vanilla vs the reference's cnn_features=1 scalar
    # bottleneck (same configs as the pixelpend-* swing-up runs).
    "pixelbal-wide": _preset(
        "PixelPendulumBalance-v0", epochs=6, steps_per_epoch=4000,
        max_ep_len=1000, buffer_size=24_000,
        **PIXEL_RECIPE,
    ),
    # Longer-budget headline run (the 24k curve was still improving
    # every epoch when its budget ended): same recipe, 40k steps.
    "pixelbal-long": _preset(
        "PixelPendulumBalance-v0", epochs=8, steps_per_epoch=4000,
        max_ep_len=1000, buffer_size=32_000,
        **PIXEL_RECIPE,
    ),
    "pixelbal-vanilla": _preset(
        "PixelPendulumBalance-v0", epochs=4, steps_per_epoch=4000,
        max_ep_len=1000, buffer_size=16_000,
        **PIXEL_CONV,
    ),
    "pixelbal-parity": _preset(
        "PixelPendulumBalance-v0", epochs=4, steps_per_epoch=4000,
        max_ep_len=1000, buffer_size=16_000,
        filters=(16, 32), kernel_sizes=(4, 3), strides=(2, 2),
        cnn_dense_size=128, cnn_features=1, normalize_pixels=False,
    ),
    # Population training (VERDICT r4 #1): 4 independent SAC seeds on
    # HalfCheetah advanced by ONE vmapped burst — the committed
    # multi-seed artifact. metrics.jsonl carries reward_m0..m3 (4 real
    # learning curves); summary.json records per-member eval stats.
    "popcheetah": _preset(
        "HalfCheetah-v5", epochs=20, steps_per_epoch=5000, max_ep_len=1000,
        buffer_size=100_000, population=4,
    ),
    # dm_control cheetah at 100k (PARITY.md "dm:cheetah:run"
    # comparison): the reference-default fixed alpha fails silently on
    # [0,1]-per-step rewards; the learned temperature and TD3 recover.
    "dmcheetah-fixed": _preset(
        "dm:cheetah:run", epochs=20, steps_per_epoch=5000, max_ep_len=1000,
        buffer_size=100_000,
    ),
    "dmcheetah-learnalpha": _preset(
        "dm:cheetah:run", epochs=20, steps_per_epoch=5000, max_ep_len=1000,
        buffer_size=100_000, learn_alpha=True,
    ),
    "dmcheetah-td3": _preset(
        "dm:cheetah:run", epochs=20, steps_per_epoch=5000, max_ep_len=1000,
        buffer_size=100_000, algorithm="td3",
        start_steps=10_000, update_after=1000,
    ),
    # Real composer wall-runner epoch (PARITY.md "Pixel wall-runner
    # end-to-end"; BASELINE config 5 geometry)
    "wallrunner-real": _preset(
        "DeepMindWallRunner-v0", eval_episodes=2,
        epochs=1, steps_per_epoch=600, start_steps=300, update_after=300,
        update_every=50, batch_size=32, buffer_size=600,
    ),
    # Long wall-runner run (VERDICT r4 #6): the parallel env pool on
    # the real composer task for hours. 1000-step epochs keep
    # metrics.jsonl fine-grained, so a wall-clock cutoff still leaves
    # a committed trend (composer+visual-SAC runs ~3 env-steps/s on
    # this 1-core image — 50k steps is a ~5h budget; the pool's
    # speedup story lives in bench.py's host_envs crossover section,
    # which a 1-core host cannot demonstrate live).
    # learn_alpha: the wall-runner pays dm_control-scale [0,1]-per-step
    # rewards, where the fixed alpha=0.2 entropy bonus swamps the
    # signal (measured on dm:cheetah:run at 100k steps — eval 0.28
    # fixed vs 309.1 learned, runs/dmcheetah-{fixed,learnalpha}); a
    # TREND run must use the learned temperature.
    "wallrunner-long": _preset(
        "DeepMindWallRunner-v0", eval_episodes=2,
        epochs=50, steps_per_epoch=1000, start_steps=1000,
        update_after=1000, update_every=50, batch_size=32,
        buffer_size=50_000, parallel_envs=True, max_ep_len=1000,
        learn_alpha=True,
    ),
}


def run_preset(name: str) -> dict:
    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # Honor JAX_PLATFORMS=cpu even when a sitecustomize hook
        # re-registers an accelerator platform over it (same
        # countermeasure as bench.py).
        jax.config.update("jax_platforms", "cpu")

    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.tracking import Tracker

    spec = PRESETS[name]
    cfg = SACConfig(**spec["overrides"])
    seed = spec["seed"]
    # Re-running a preset replaces its artifacts (metrics.jsonl is
    # append-mode; a stale run must not bleed into the fresh curve).
    import shutil

    shutil.rmtree(os.path.join("runs", name, f"s{seed}"), ignore_errors=True)
    tracker = Tracker(experiment=name, run_id=f"s{seed}", root="runs")
    tracker.log_params(dataclasses.asdict(cfg))
    t0 = time.time()
    tr = Trainer(
        spec["env"], cfg, mesh=make_mesh(dp=1), tracker=tracker, seed=seed
    )
    metrics = tr.train()
    ev = tr.evaluate(
        episodes=spec["eval_episodes"], deterministic=True, seed=seed + 12345
    )
    summary = {
        "preset": name,
        "env": spec["env"],
        "seed": seed,
        "steps": cfg.epochs * cfg.steps_per_epoch,
        "algorithm": cfg.algorithm,
        "compute_dtype": cfg.compute_dtype,
        "history_len": cfg.history_len,
        "train_return_final_epoch": metrics.get("reward"),
        "eval_return_mean": ev["ep_ret_mean"],
        "eval_return_std": ev["ep_ret_std"],
        "eval_ep_len_mean": ev["ep_len_mean"],
        "eval_episodes": spec["eval_episodes"],
        "wall_s": round(time.time() - t0, 1),
    }
    if "per_member" in ev:
        # Population runs: the N independent seed results.
        summary["per_member"] = ev["per_member"]
    with open(tracker.run_dir / "summary.json", "w") as f:
        json.dump(summary, f, indent=2)
    tr.close()
    print(json.dumps(summary), flush=True)
    return summary


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("preset", nargs="?", choices=sorted(PRESETS))
    p.add_argument("--list", action="store_true")
    args = p.parse_args()
    if args.list or args.preset is None:
        print("\n".join(sorted(PRESETS)))
        return
    run_preset(args.preset)


if __name__ == "__main__":
    main()
