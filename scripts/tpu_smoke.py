"""Real-TPU smoke: compile-and-run the paths that CPU tests cannot reach.

The CI suite runs everything on the 8-virtual-device CPU mesh; the
Pallas kernels there execute in interpret mode only. This script runs
on the real chip (no platform forcing):

1. flash attention forward+backward (Mosaic compile) vs the dense
   reference, causal and non-causal, head-dim padding;
2. one fused SAC update_burst at the benchmark configuration;
3. a sequence-SAC update_burst (flash attention fwd+bwd inside the
   actual training path);
4. a visual update_burst at the real wall-runner geometry (168
   features + 64x64x3 uint8 frames, act_dim 56, NHWC convs);
5. one fused on-device HalfCheetah-twin epoch.

Prints one OK/FAIL line per stage and exits non-zero on any failure.
Run: ``python scripts/tpu_smoke.py`` (first compile ~20-40s).
"""

from __future__ import annotations

import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FAILURES = []


def stage(name):
    def deco(fn):
        def run():
            try:
                fn()
                print(f"OK   {name}", flush=True)
            except Exception:
                FAILURES.append(name)
                print(f"FAIL {name}", flush=True)
                traceback.print_exc()
        return run
    return deco


@stage("flash_attention fwd+bwd (pallas, real chip)")
def smoke_flash():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.ops.attention import (
        flash_attention,
        reference_attention,
    )

    # (None, None) blocks = the production auto path (512-capped; the
    # t=1024 row resolves to 512 blocks, the chip-sweep optimum the
    # defaults now ship) alongside an explicit-128 row.
    for causal, t, d, bq, bk in [
        (True, 256, 64, None, None),
        (False, 256, 64, 128, 128),
        (True, 128, 48, None, None),
        (True, 1024, 64, None, None),
    ]:
        ks = jax.random.split(jax.random.key(0), 4)
        q, k, v = (
            jax.random.normal(kk, (2, 4, t, d), jnp.float32) for kk in ks[:3]
        )
        g = jax.random.normal(ks[3], (2, 4, t, d), jnp.float32)
        interp = os.environ.get("TAC_SMOKE_CPU") == "1"  # CPU dry-run only
        out_f, vjp_f = jax.vjp(
            lambda q, k, v: flash_attention(q, k, v, causal, bq, bk, interp),
            q, k, v,
        )
        out_r, vjp_r = jax.vjp(
            lambda q, k, v: reference_attention(q, k, v, causal=causal), q, k, v
        )
        np.testing.assert_allclose(
            np.asarray(out_f), np.asarray(out_r), atol=2e-2, rtol=2e-2
        )
        for a, b in zip(vjp_f(g), vjp_r(g)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-2, rtol=5e-2
            )


@stage("fused update_burst at bench config")
def smoke_burst():
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.buffer import init_replay_buffer, push
    from torch_actor_critic_tpu.core.types import Batch
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(batch_size=64)
    sac = SAC(cfg, Actor(act_dim=6), DoubleCritic(), 6)
    state = sac.init_state(jax.random.key(0), jnp.zeros((17,)))
    buf = init_replay_buffer(10_000, jax.ShapeDtypeStruct((17,), jnp.float32), 6)
    ks = jax.random.split(jax.random.key(1), 5)
    chunk = Batch(
        states=jax.random.normal(ks[0], (500, 17)),
        actions=jnp.tanh(jax.random.normal(ks[1], (500, 6))),
        rewards=jax.random.normal(ks[2], (500,)),
        next_states=jax.random.normal(ks[3], (500, 17)),
        done=jnp.zeros((500,)),
    )
    push_j = jax.jit(push, donate_argnums=(0,))
    burst_j = jax.jit(sac.update_burst, static_argnums=(3,))
    buf = push_j(buf, chunk)
    state, buf, m = burst_j(
        state, buf, chunk, 50
    )
    assert bool(jnp.isfinite(m["loss_q"])), m


@stage("sequence-SAC update_burst (flash fwd+bwd in the training path)")
def smoke_sequence_burst():
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.buffer import init_replay_buffer, push
    from torch_actor_critic_tpu.core.types import Batch
    from torch_actor_critic_tpu.models import SequenceActor, SequenceDoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig

    horizon, obs_dim, act_dim = 16, 3, 1
    cfg = SACConfig(batch_size=32, history_len=horizon)
    actor = SequenceActor(act_dim=act_dim, max_len=horizon)
    critic = SequenceDoubleCritic(max_len=horizon)
    sac = SAC(cfg, actor, critic, act_dim)
    state = sac.init_state(jax.random.key(0), jnp.zeros((horizon, obs_dim)))
    buf = init_replay_buffer(
        2_000, jax.ShapeDtypeStruct((horizon, obs_dim), jnp.float32), act_dim
    )
    ks = jax.random.split(jax.random.key(1), 5)
    chunk = Batch(
        states=jax.random.normal(ks[0], (200, horizon, obs_dim)),
        actions=jnp.tanh(jax.random.normal(ks[1], (200, act_dim))),
        rewards=jax.random.normal(ks[2], (200,)),
        next_states=jax.random.normal(ks[3], (200, horizon, obs_dim)),
        done=jnp.zeros((200,)),
    )
    push_j = jax.jit(push, donate_argnums=(0,))
    burst_j = jax.jit(sac.update_burst, static_argnums=(3,))
    buf = push_j(buf, chunk)
    state, buf, m = burst_j(
        state, buf, chunk, 10
    )
    assert bool(jnp.isfinite(m["loss_q"])), m
    assert bool(jnp.isfinite(m["loss_pi"])), m


@stage("visual update_burst at wall-runner geometry (NHWC uint8 on chip)")
def smoke_visual_burst():
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.buffer import init_visual_replay_buffer, push
    from torch_actor_critic_tpu.core.types import Batch, MultiObservation
    from torch_actor_critic_tpu.models import VisualActor, VisualDoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig

    # The real wall-runner observation geometry (BASELINE config 5):
    # 168 proprioceptive features + a 64x64x3 uint8 egocentric frame.
    feat, frame, act_dim, n = 168, (64, 64, 3), 56, 128
    cfg = SACConfig(batch_size=32)
    sac = SAC(
        cfg, VisualActor(act_dim=act_dim), VisualDoubleCritic(), act_dim
    )
    state = sac.init_state(
        jax.random.key(0),
        MultiObservation(
            features=jnp.zeros((feat,)), frame=jnp.zeros(frame, jnp.uint8)
        ),
    )
    buf = init_visual_replay_buffer(2_000, feat, frame, act_dim)
    ks = jax.random.split(jax.random.key(1), 6)

    def obs(key_f, key_p):
        return MultiObservation(
            features=jax.random.normal(key_f, (n, feat)),
            frame=jax.random.randint(key_p, (n, *frame), 0, 256, jnp.uint8),
        )

    chunk = Batch(
        states=obs(ks[0], ks[1]),
        actions=jnp.tanh(jax.random.normal(ks[2], (n, act_dim))),
        rewards=jax.random.normal(ks[3], (n,)),
        next_states=obs(ks[4], ks[5]),
        done=jnp.zeros((n,)),
    )
    push_j = jax.jit(push, donate_argnums=(0,))
    burst_j = jax.jit(sac.update_burst, static_argnums=(3,))
    buf = push_j(buf, chunk)
    state, buf, m = burst_j(
        state, buf, chunk, 10
    )
    assert bool(jnp.isfinite(m["loss_q"])), m
    assert bool(jnp.isfinite(m["loss_pi"])), m


@stage("on-device HalfCheetah-twin fused epoch")
def smoke_ondevice():
    from torch_actor_critic_tpu.sac.ondevice import benchmark_on_device

    out = benchmark_on_device("cheetah")
    assert "error" not in out, out
    print(f"     on-device: {out}", flush=True)


def main():
    import jax

    if os.environ.get("TAC_SMOKE_CPU") == "1":
        # CPU dry-run of the script itself (kernels go interpret-path
        # via the auto dispatch); the real run uses the default backend.
        jax.config.update("jax_platforms", "cpu")
    print(f"devices: {jax.devices()}", flush=True)
    smoke_flash()
    smoke_burst()
    smoke_sequence_burst()
    smoke_visual_burst()
    smoke_ondevice()
    if FAILURES:
        print(f"FAILED stages: {FAILURES}", flush=True)
        return 1
    print("ALL TPU SMOKE STAGES OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
