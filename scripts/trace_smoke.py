"""End-to-end smoke of the observability stack: telemetry + profiler.

Runs a tiny CPU training job through the real CLI entry point with
telemetry enabled and a ``--profile-epochs 1:2`` window, then asserts
the contract docs/OBSERVABILITY.md promises:

- ``<run_dir>/telemetry.jsonl`` exists, every line is strict JSON, and
  there is one ``epoch`` event per epoch with the full 8-phase
  taxonomy whose per-phase sums cover ~the epoch wall time;
- ``<run_dir>/trace`` holds a TensorBoard/xprof-loadable XLA trace
  (``plugins/profile/<ts>/*``) captured over exactly the window;
- ``<run_dir>/metrics.jsonl`` rows carry the save/sentinel accounting
  metrics and parse as strict JSON.

The ``make trace-smoke`` gate; ~60s on a 2-thread CPU host.
"""

import json
import os
import sys
import tempfile
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PHASES = (
    "act", "env_step", "stage", "place_chunk", "burst_dispatch",
    "drain", "sentinel", "checkpoint",
)


def fail(msg):
    print(f"[trace-smoke] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from torch_actor_critic_tpu.train import main as train_main

    root = Path(tempfile.mkdtemp(prefix="trace_smoke_"))
    train_main([
        "--environment", "Pendulum-v1",
        "--devices", "1",
        "--runs-root", str(root),
        "--epochs", "2",
        "--steps-per-epoch", "60",
        "--start-steps", "20",
        "--update-after", "20",
        "--update-every", "10",
        "--batch-size", "16",
        "--buffer-size", "500",
        "--hidden-sizes", "16,16",
        "--max-ep-len", "100",
        "--telemetry", "true",
        "--profile-epochs", "1:2",
    ])
    run_dir = next((root / "Default").iterdir())
    print(f"[trace-smoke] run dir: {run_dir}")

    # --- telemetry JSONL stream ---
    tpath = run_dir / "telemetry.jsonl"
    if not tpath.exists():
        fail(f"no telemetry stream at {tpath}")
    events = [json.loads(line) for line in tpath.read_text().splitlines()]
    epochs = [e for e in events if e["type"] == "epoch"]
    if events[0]["type"] != "run_start" or events[0]["phases"] != list(PHASES):
        fail(f"bad run_start header: {events[0]}")
    if len(epochs) != 2:
        fail(f"expected 2 epoch events, got {len(epochs)}")
    for ev in epochs:
        missing = [p for p in PHASES if p not in ev["phases"]]
        if missing:
            fail(f"epoch {ev['epoch']} missing phases {missing}")
        covered = sum(p["total_s"] for p in ev["phases"].values())
        # The phases partition the epoch: their sums must cover ~the
        # wall time (scheduler noise allows a small under-run, and
        # nothing can exceed it by more than jitter).
        if not 0.8 * ev["wall_s"] <= covered <= 1.1 * ev["wall_s"]:
            fail(
                f"epoch {ev['epoch']}: phase sums {covered:.4f}s do not "
                f"cover wall_s {ev['wall_s']:.4f}s"
            )
    print(f"[trace-smoke] telemetry ok: {len(epochs)} epoch events, "
          f"phase coverage verified")

    # --- XLA trace (the --profile-epochs window) ---
    profile_dir = run_dir / "trace" / "plugins" / "profile"
    if not profile_dir.is_dir():
        fail(f"no profiler capture under {profile_dir}")
    captures = [
        f for d in profile_dir.iterdir() if d.is_dir()
        for f in d.iterdir()
    ]
    if not captures:
        fail(f"profiler capture directory {profile_dir} is empty")
    print(f"[trace-smoke] trace ok: {len(captures)} artifact(s) under "
          f"{profile_dir}")

    # --- metrics mirror carries the epoch-accounting satellites ---
    rows = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    for row in rows:
        for key in ("sentinel_s", "save_s", "env_steps_per_sec"):
            if key not in row:
                fail(f"metrics row missing {key}: {row}")
    print("[trace-smoke] metrics mirror ok "
          f"({len(rows)} rows with save/sentinel accounting)")
    print("[trace-smoke] PASS")


if __name__ == "__main__":
    main()
