"""End-to-end smoke of the scenarios/ subsystem through the real CLI.

Trains every scenario pillar for a few epochs on CPU via
``python -m torch_actor_critic_tpu.train`` and asserts the contract
docs/SCENARIOS.md promises:

- **multi-agent** — finite losses plus per-agent reward curves
  (``reward_a0..A-1``) in metrics.jsonl;
- **procedural** — the hurdle-runner trains with finite losses and a
  finite mean return (level regeneration riding the fused loop);
- **multi-task** — schema-valid per-task metrics (``reward_t{i}`` /
  ``episodes_t{i}`` for every task, per-task episode counts summing to
  the total) from the striped-replay run, AND a **bitwise resume**: a
  population run interrupted at epoch 1 and resumed reproduces the
  uninterrupted run's member loss curves exactly (the population
  checkpoint carries env states, act keys and the striped rings).

The ``make scenario-smoke`` gate; ~2-3 min on a 2-thread CPU host.
"""

import json
import math
import os
import sys
import tempfile
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fail(msg):
    print(f"[scenario-smoke] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


BASE_ARGS = [
    "--on-device", "true",
    "--devices", "1",
    "--steps-per-epoch", "100",
    "--update-every", "10",
    "--start-steps", "20",
    "--update-after", "0",
    "--batch-size", "15",
    "--buffer-size", "3000",
    "--hidden-sizes", "16,16",
    "--on-device-envs", "4",
    "--save-every", "1",
]


def read_rows(run_dir: Path):
    return [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]


def run_dir_of(root: Path):
    return next((root / "Default").iterdir())


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from torch_actor_critic_tpu.train import main as train_main

    # --- multi-agent: per-agent curves under the fused loop ---
    root = Path(tempfile.mkdtemp(prefix="scen_ma_"))
    final = train_main([
        "--environment", "multi-pendulum-2",
        "--runs-root", str(root), "--epochs", "2", *BASE_ARGS,
    ])
    for key in ("loss_q", "loss_pi"):
        if not math.isfinite(final[key]):
            fail(f"multi-agent {key} non-finite: {final[key]}")
    rows = read_rows(run_dir_of(root))
    for row in rows:
        for agent in range(2):
            if f"reward_a{agent}" not in row:
                fail(f"multi-agent row missing reward_a{agent}: {sorted(row)}")
    # Episodes finish from epoch 1 (200-step episodes, 4 envs x 100
    # steps/epoch + warmup): the last row's per-agent rewards are real.
    last = rows[-1]
    for agent in range(2):
        v = last[f"reward_a{agent}"]
        if v is None or not math.isfinite(v):
            fail(f"reward_a{agent} non-finite in final epoch: {v!r}")
    print("[scenario-smoke] multi-agent ok: per-agent curves "
          f"a0={last['reward_a0']:.1f} a1={last['reward_a1']:.1f}")

    # --- procedural: fresh level per episode, fused loop ---
    root = Path(tempfile.mkdtemp(prefix="scen_proc_"))
    final = train_main([
        "--environment", "hurdle-runner",
        "--runs-root", str(root), "--epochs", "2", *BASE_ARGS,
        # Hurdle episodes truncate at 300 steps; 2 x 200 steps x 4 envs
        # finishes episodes inside the run (argparse keeps the last
        # occurrence, overriding BASE_ARGS' 100).
        "--steps-per-epoch", "200",
    ])
    if not math.isfinite(final["loss_q"]):
        fail(f"procedural loss_q non-finite: {final['loss_q']}")
    if not math.isfinite(final["reward"]):
        fail(f"procedural reward non-finite: {final['reward']}")
    print(f"[scenario-smoke] procedural ok: reward={final['reward']:.1f}")

    # --- multi-task: per-task metric schema ---
    root = Path(tempfile.mkdtemp(prefix="scen_mt_"))
    final = train_main([
        "--environment", "pendulum-multitask",
        "--runs-root", str(root), "--epochs", "3", *BASE_ARGS,
        "--on-device-envs", "8",
    ])
    n_tasks = 3
    rows = read_rows(run_dir_of(root))
    for row in rows:
        total = 0.0
        for task in range(n_tasks):
            for base in ("reward_t", "episodes_t"):
                if f"{base}{task}" not in row:
                    fail(f"multi-task row missing {base}{task}: {sorted(row)}")
            total += row[f"episodes_t{task}"]
        if total != row["episodes"]:
            fail(
                f"per-task episodes {total} != total {row['episodes']}"
            )
        if f"reward_t{n_tasks}" in row:
            fail(f"phantom task {n_tasks} in metrics: {sorted(row)}")
    # Episodes truncate at 200 steps, so not every epoch finishes one
    # (a no-episode epoch honestly reports null); SOME epoch must have
    # produced finite per-task rewards.
    finite_t = sorted({
        t for row in rows for t in range(n_tasks)
        if row[f"reward_t{t}"] is not None
        and math.isfinite(row[f"reward_t{t}"])
    })
    if not finite_t:
        fail(f"no task produced a finite reward curve: {rows}")
    print(f"[scenario-smoke] multi-task ok: schema-valid per-task "
          f"metrics, finite tasks {finite_t}")

    # --- bitwise resume: interrupted+resumed == uninterrupted ---
    # The population driver checkpoints the COMPLETE scenario state
    # (stacked learners, striped rings, env states incl. task ids,
    # act keys), so a resumed run must reproduce the uninterrupted
    # member curves exactly.
    def population_run(root, epochs):
        return train_main([
            "--environment", "pendulum-multitask",
            "--runs-root", str(root), "--epochs", str(epochs),
            "--population", "2", *BASE_ARGS,
        ])

    root_full = Path(tempfile.mkdtemp(prefix="scen_full_"))
    population_run(root_full, 3)
    rows_full = read_rows(run_dir_of(root_full))

    root_cut = Path(tempfile.mkdtemp(prefix="scen_cut_"))
    population_run(root_cut, 1)  # "interrupted" after epoch 0's save
    cut_dir = run_dir_of(root_cut)
    # Resume runs config.epochs (1) more epochs per invocation.
    for _ in range(2):
        train_main(["--run", cut_dir.name, "--runs-root", str(root_cut)])
    rows_cut = read_rows(cut_dir)
    if len(rows_cut) != len(rows_full):
        fail(
            f"resumed run logged {len(rows_cut)} epochs vs "
            f"{len(rows_full)} uninterrupted"
        )
    compare = [
        k for k in rows_full[-1]
        if k.startswith(("loss_q_m", "loss_pi_m", "reward_m", "episodes"))
    ]
    for full_row, cut_row in zip(rows_full, rows_cut):
        for k in compare:
            if full_row.get(k) != cut_row.get(k):
                fail(
                    f"resume not bitwise at epoch {full_row['step']}: "
                    f"{k} {full_row.get(k)!r} != {cut_row.get(k)!r}"
                )
    print(f"[scenario-smoke] resume ok: {len(compare)} member-metric "
          f"keys bitwise across {len(rows_full)} epochs")
    print("[scenario-smoke] PASS")


if __name__ == "__main__":
    main()
