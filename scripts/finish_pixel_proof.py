"""Complete the round-5 pixel proof artifact whose eval phase was lost.

The 2026-08-01 06:08 UTC 120k-step fused DrQ run trained to completion
(train block in ``train_proof_pixel_20260801T060825Z.json``) but its
in-process eval never ran: the pre-fix exactly-one-new-run guard saw a
second run directory (the concurrent cheetah smoke) and raised. The
checkpoint is intact, so this script performs the IDENTICAL eval the
proof would have run (run_agent, 10 deterministic episodes, seed 0,
host PixelPendulumBalance-v0) and appends the same eval block.
"""

import json
import sys

sys.path.insert(0, ".")

ARTIFACT = "runs/train_proof/train_proof_pixel_20260801T060825Z.json"
RUN_ID = "6f628143c1694836"


def main():
    from torch_actor_critic_tpu.run_agent import main as eval_main

    eval_metrics = eval_main([
        "--run", RUN_ID,
        "--runs-root", "runs/train_proof",
        "--episodes", "10",
        "--headless",
        "--seed", "0",
    ])
    out = json.load(open(ARTIFACT))
    out["eval"] = {
        "episodes": 10,
        "ep_ret_mean": round(float(eval_metrics["ep_ret_mean"]), 1),
        "ep_ret_std": round(float(eval_metrics["ep_ret_std"]), 1),
        "host_env": "PixelPendulumBalance-v0",
        "solved_band_threshold": -400.0,
        "solved": float(eval_metrics["ep_ret_mean"]) > -400.0,
        "random_policy_baseline": -873.7,
        "note": (
            "eval re-run post-hoc by scripts/finish_pixel_proof.py: the "
            "in-process eval died on the pre-fix one-new-run guard "
            "(concurrent proof tasks now use per-task experiment dirs); "
            "same protocol, same checkpoint, same seed"
        ),
    }
    json.dump(out, open(ARTIFACT, "w"), indent=1, sort_keys=True)
    print(json.dumps(out["eval"], indent=1))


if __name__ == "__main__":
    main()
