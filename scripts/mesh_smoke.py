"""End-to-end smoke of the named-mesh GSPMD substrate on forced devices.

Forces a 4-device CPU backend (``--xla_force_host_platform_device_count``,
the same shim tier-1 uses) and exercises the three scale-out paths the
PR-8 rebuild unlocked, through the real entry points:

- the data-parallel update burst on a dp=4 mesh (jit-with-sharding, no
  shard_map): params replicated across all 4 devices, finite losses,
  replica-desync canary (``param_norm_skew``) reading exactly 0.0;
- the dp+fsdp hybrid burst (dp=2 x fsdp=2, threshold forced to 0 so the
  tiny model really shards) — the path the legacy substrate version-
  gated off — matching the all-replicated burst allclose;
- ``--population 8`` member-sharded fused training END-TO-END through
  the ``train.py`` CLI on the dp=4 mesh: members spread 2 per device,
  N distinct finite curves in metrics.jsonl, and a bitwise ``--run``
  resume of the sharded population checkpoint.

The ``make mesh-smoke`` gate; ~2 min on a 2-thread CPU host.
"""

import json
import os
import sys
import tempfile
from pathlib import Path

# Must precede the first jax import anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_DEV = 4
POP = 8


def fail(msg):
    print(f"[mesh-smoke] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def ok(msg):
    print(f"[mesh-smoke] {msg}", flush=True)


def _chunk(key, n_dev, per_dev, obs_dim, act_dim):
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.core.types import Batch

    ks = jax.random.split(key, 5)
    shape = (n_dev, per_dev)
    return Batch(
        states=jax.random.normal(ks[0], shape + (obs_dim,)),
        actions=jnp.tanh(jax.random.normal(ks[1], shape + (act_dim,))),
        rewards=jax.random.normal(ks[2], shape),
        next_states=jax.random.normal(ks[3], shape + (obs_dim,)),
        done=jnp.zeros(shape),
    )


def _dp(sac, mesh, **kw):
    from torch_actor_critic_tpu.parallel import DataParallelSAC

    return DataParallelSAC(sac, mesh, **kw)


def check_dp_burst():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.parallel import (
        init_sharded_buffer,
        make_mesh,
        shard_chunk,
    )
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig

    obs_dim, act_dim = 4, 2
    cfg = SACConfig(
        hidden_sizes=(32, 32), batch_size=8, diagnostics="light"
    )
    sac = SAC(
        cfg,
        Actor(act_dim=act_dim, hidden_sizes=cfg.hidden_sizes),
        DoubleCritic(hidden_sizes=cfg.hidden_sizes),
        act_dim,
    )
    dp = _dp(sac, make_mesh(dp=N_DEV))
    state = dp.init_state(jax.random.key(0), jnp.zeros((obs_dim,)))
    buf = init_sharded_buffer(
        128, jax.ShapeDtypeStruct((obs_dim,), jnp.float32), act_dim, dp.mesh
    )
    chunk = shard_chunk(
        _chunk(jax.random.key(1), N_DEV, 32, obs_dim, act_dim), dp.mesh
    )
    state, buf, m = dp.update_burst(state, buf, chunk, 4)
    if int(state.step) != 4 or not np.isfinite(float(m["loss_q"])):
        fail(f"dp burst broken: step={int(state.step)}, m={m}")
    leaf = jax.tree_util.tree_leaves(state.actor_params)[0]
    if len(leaf.sharding.device_set) != N_DEV or not leaf.sharding.is_fully_replicated:
        fail(f"params not replicated across {N_DEV} devices: {leaf.sharding}")
    if float(m["diag/param_norm_skew"]) != 0.0:
        fail(f"replica desync canary nonzero: {m['diag/param_norm_skew']}")
    ok(f"dp={N_DEV} burst: loss_q={float(m['loss_q']):.4f}, "
       "params replicated, param_norm_skew=0.0")


def check_hybrid_burst():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.parallel import (
        init_sharded_buffer,
        make_mesh,
        shard_chunk,
    )
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.config import SACConfig

    obs_dim, act_dim = 4, 2

    if hasattr(jax, "shard_map"):
        ok("note: native jax.shard_map present; the point of this check "
           "is that the hybrid no longer needs it")

    def run(fsdp):
        cfg = SACConfig(hidden_sizes=(32, 32), batch_size=8)
        sac = SAC(
            cfg,
            Actor(act_dim=act_dim, hidden_sizes=cfg.hidden_sizes),
            DoubleCritic(hidden_sizes=cfg.hidden_sizes),
            act_dim,
        )
        dp = _dp(
            sac, make_mesh(dp=2, fsdp=fsdp), fsdp_min_bytes=0
        )
        state = dp.init_state(jax.random.key(0), jnp.zeros((obs_dim,)))
        if fsdp > 1:
            kern = state.actor_params["params"]["MLP_0"]["Dense_0"]["col"][
                "kernel"
            ]
            if kern.sharding.is_fully_replicated:
                fail("fsdp=2 kernel not actually sharded")
        buf = init_sharded_buffer(
            64, jax.ShapeDtypeStruct((obs_dim,), jnp.float32), act_dim,
            dp.mesh,
        )
        chunk = shard_chunk(
            _chunk(jax.random.key(1), 2, 16, obs_dim, act_dim), dp.mesh
        )
        state, buf, m = dp.update_burst(state, buf, chunk, 3)
        return state, m

    s_f, m_f = run(fsdp=2)
    s_r, m_r = run(fsdp=1)
    import numpy as np

    for a, b in zip(
        jax.tree_util.tree_leaves(s_f.critic_params),
        jax.tree_util.tree_leaves(s_r.critic_params),
    ):
        if not np.allclose(np.asarray(a), np.asarray(b), atol=1e-5):
            fail("dp+fsdp hybrid diverged from the replicated burst")
    ok(f"dp=2 x fsdp=2 hybrid burst (no version gate): "
       f"loss_q={float(m_f['loss_q']):.4f} == replicated "
       f"{float(m_r['loss_q']):.4f}")


def check_population_sharded():
    import jax
    import numpy as np

    sys.path.insert(0, REPO)
    from torch_actor_critic_tpu.train import main as train_main

    root = Path(tempfile.mkdtemp(prefix="mesh_smoke_"))
    args = [
        "--environment", "Pendulum-v1",
        "--on-device", "true",
        "--population", str(POP),
        "--telemetry", "true",
        "--runs-root", str(root),
        "--epochs", "2",
        "--steps-per-epoch", "60",
        "--update-every", "20",
        "--start-steps", "20",
        "--on-device-envs", "2",
        "--buffer-size", "3000",
        "--hidden-sizes", "16,16",
        "--batch-size", "8",
        "--save-every", "1",
        "--experiment", "mesh-smoke",
    ]
    metrics = train_main(args)
    for i in range(POP):
        v = metrics.get(f"loss_q_m{i}")
        if v is None or not np.isfinite(v):
            fail(f"member {i} curve missing/not finite: {v}")
    if len({round(metrics[f'loss_q_m{i}'], 6) for i in range(POP)}) < 2:
        fail("member curves are one curve copied N times")
    runs = list(root.glob("*/*/metrics.jsonl"))
    if not runs:
        fail(f"no metrics.jsonl under {root}")
    rows = [json.loads(line) for line in runs[0].read_text().splitlines()]
    if len(rows) < 2:
        fail(f"expected 2 epochs of metrics rows, got {len(rows)}")
    run_id = runs[0].parent.name
    ok(f"population={POP} sharded over dp={jax.device_count()} via CLI: "
       f"{len(rows)} epochs, {POP} distinct finite curves (run {run_id})")

    # Bitwise resume of the sharded population checkpoint: one more
    # epoch from the saved state must land where a fresh read of the
    # final metrics did.
    resumed = train_main([
        "--run", run_id,
        "--runs-root", str(root),
        "--experiment", "mesh-smoke",
        "--epochs", "1",
    ])
    for i in range(POP):
        v = resumed.get(f"loss_q_m{i}")
        if v is None or not np.isfinite(v):
            fail(f"resumed member {i} curve missing/not finite: {v}")
    ok(f"sharded population checkpoint resumed (run {run_id})")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.device_count() != N_DEV:
        fail(
            f"expected {N_DEV} forced CPU devices, got {jax.device_count()} "
            "(XLA_FLAGS not honored — is jax imported before this script "
            "set the env?)"
        )
    check_dp_burst()
    check_hybrid_burst()
    check_population_sharded()
    ok("OK")


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    main()
