"""End-to-end smoke of population-fused training with on-device PBT.

Runs a tiny CPU population through the real CLI entry point
(``--on-device true --population 4 --pbt-every 1 --telemetry true``)
and asserts the contract docs/SCALING.md "population" promises:

- N DISTINCT finite learning curves: every ``metrics.jsonl`` row
  carries ``loss_q_m0..N-1`` / ``reward_m0..N-1`` member curves plus
  the suffix-keyed aggregates, all finite, and the members are not one
  curve copied N times;
- at least one PBT exploit event: a schema-valid ``pbt`` record in
  ``telemetry.jsonl`` whose ``exploited`` list is non-empty, with
  per-member hyperparameters that actually diverged (explore);
- a successful ``--run`` resume of the population checkpoint (stacked
  state + member PRNG keys + per-member hyperparams).

The ``make pop-smoke`` gate; ~90s on a 2-thread CPU host.
"""

import json
import math
import os
import sys
import tempfile
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = 4
EPOCHS = 3


def fail(msg):
    print(f"[pop-smoke] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from torch_actor_critic_tpu.train import main as train_main

    root = Path(tempfile.mkdtemp(prefix="pop_smoke_"))
    # The on-device pendulum truncates at its own max_episode_steps
    # (200); sized so every env finishes an episode during epoch 1
    # (20 warmup + 2x100 steps > 200) — the exploit gate (every member
    # ranked) opens at that pbt_every boundary.
    final = train_main([
        "--environment", "Pendulum-v1",
        "--on-device", "true",
        "--population", str(N),
        "--pbt-every", "1",
        "--pbt-quantile", "0.25",
        "--telemetry", "true",
        "--devices", "1",
        "--runs-root", str(root),
        "--epochs", str(EPOCHS),
        "--steps-per-epoch", "100",
        "--update-every", "10",
        "--start-steps", "20",
        "--update-after", "0",
        "--batch-size", "16",
        "--buffer-size", "800",
        "--hidden-sizes", "16,16",
        "--on-device-envs", "2",
    ])
    run_dir = next((root / "Default").iterdir())
    print(f"[pop-smoke] run dir: {run_dir}")

    # --- N distinct finite learning curves ---
    rows = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    if len(rows) != EPOCHS:
        fail(f"expected {EPOCHS} metrics rows, got {len(rows)}")
    for row in rows:
        for base in ("loss_q", "loss_pi", "reward"):
            for i in range(N):
                key = f"{base}_m{i}"
                if key not in row:
                    fail(f"metrics row missing {key}")
                if base != "reward" and row[key] is None:
                    # tracker maps non-finite to null; reward is
                    # legitimately null for a no-episode epoch
                    fail(f"{key} is null (non-finite) in {row}")
    curves = [
        tuple(row[f"loss_q_m{i}"] for row in rows) for i in range(N)
    ]
    for i, c in enumerate(curves):
        if not all(math.isfinite(v) for v in c):
            fail(f"member {i} loss_q curve non-finite: {c}")
    if len(set(curves)) != N:
        fail(f"member curves are not distinct: {curves}")
    if any(f"loss_q_m{N}" in row for row in rows):
        fail(f"phantom member {N} in metrics")
    print(f"[pop-smoke] metrics ok: {N} distinct finite member curves "
          f"over {len(rows)} epochs")

    # --- PBT exploit events, schema-valid ---
    events = [
        json.loads(line)
        for line in (run_dir / "telemetry.jsonl").read_text().splitlines()
    ]
    pbt = [e for e in events if e.get("type") == "pbt"]
    if not pbt:
        fail("no pbt telemetry events")
    for e in pbt:
        missing = {"epoch", "exploited", "src", "ready", "return_ema",
                   "hyperparams"} - set(e)
        if missing:
            fail(f"pbt event missing {missing}: {e}")
        if len(e["src"]) != N or len(e["return_ema"]) != N:
            fail(f"pbt event arrays not member-shaped: {e}")
    exploits = [e for e in pbt if e["exploited"]]
    if not exploits:
        fail(f"no exploit fired in {len(pbt)} pbt steps "
             f"(ready={[e['ready'] for e in pbt]})")
    ev = exploits[0]
    for loser in ev["exploited"]:
        if ev["src"][loser] == loser:
            fail(f"exploited member {loser} has itself as src: {ev}")
    hp = ev["hyperparams"]
    if not hp:
        fail("pbt event carries no hyperparameters")
    for k, v in hp.items():
        if len(v) != N:
            fail(f"hyperparam {k} not per-member: {v}")
        if len(set(v)) == 1:
            fail(f"hyperparam {k} identical across members (no explore): {v}")
    print(f"[pop-smoke] pbt ok: {len(pbt)} steps, "
          f"{sum(len(e['exploited']) for e in exploits)} exploits, "
          f"hyperparams diverged: {sorted(hp)}")

    # --- resume the population checkpoint ---
    resumed = train_main(
        ["--run", run_dir.name, "--runs-root", str(root)]
    )
    for i in range(N):
        v = resumed.get(f"loss_q_m{i}")
        if v is None or not math.isfinite(float(v)):
            fail(f"resumed loss_q_m{i} non-finite: {v!r}")
    rows_after = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    if len(rows_after) <= len(rows):
        fail(f"resume logged no new epochs ({len(rows_after)} rows)")
    print(f"[pop-smoke] resume ok: {len(rows_after) - len(rows)} more "
          f"epochs, {N} members still finite")
    print(f"[pop-smoke] final: "
          f"{ {k: round(v, 3) for k, v in final.items() if k.startswith('loss_q_m')} }")
    print("[pop-smoke] PASS")


if __name__ == "__main__":
    main()
