"""End-to-end smoke of the serving CLI: real checkpoint, real HTTP.

Writes a real TrainState checkpoint into a temp dir, launches
``python serve.py --ckpt-dir ... --port 0`` as a subprocess (the exact
operator entry point), round-trips ``/act`` and ``/healthz`` over
loopback, and exits nonzero on any failure — the `make serve-smoke`
gate. Runs on CPU in ~30s; no accelerator required.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
from urllib import request as urlreq

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBS_DIM, ACT_DIM = 17, 6


def fail(msg, proc=None):
    print(f"[serve-smoke] FAIL: {msg}", file=sys.stderr)
    if proc is not None:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=10)
            print(out[-3000:], file=sys.stderr)
        except subprocess.TimeoutExpired:
            proc.kill()
    sys.exit(1)


def main():
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    tmp = tempfile.mkdtemp(prefix="serve_smoke_")
    ckpt_dir = os.path.join(tmp, "ckpts")
    cfg = SACConfig(hidden_sizes=(32, 32))
    sac = SAC(
        cfg,
        Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32)),
        DoubleCritic(hidden_sizes=(32, 32)),
        ACT_DIM,
    )
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    ck = Checkpointer(ckpt_dir, save_buffer=False)
    ck.save(0, state, extra={"config": cfg.to_json()}, wait=True)
    ck.close()
    print(f"[serve-smoke] checkpoint written: {ckpt_dir}")

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + (
            os.pathsep + os.environ["PYTHONPATH"]
            if os.environ.get("PYTHONPATH") else ""
        ),
        PALLAS_AXON_POOL_IPS="",  # keep accelerator hooks out (cf.
        # tests/test_multihost.py's subprocess hygiene)
    )
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "serve.py"),
            "--ckpt-dir", ckpt_dir,
            "--obs-dim", str(OBS_DIM), "--act-dim", str(ACT_DIM),
            "--port", "0",  # random ephemeral port, printed at startup
            "--max-batch", "8", "--max-wait-ms", "2",
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )

    # The CLI prints one JSON line {"serving": "http://...", ...} once
    # the model is loaded and every bucket is warm.
    address, deadline = None, time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                fail(f"server exited rc={proc.returncode} before ready", proc)
            time.sleep(0.1)
            continue
        sys.stderr.write("[server] " + line)
        if line.startswith("{"):
            try:
                address = json.loads(line)["serving"]
                break
            except (json.JSONDecodeError, KeyError):
                continue
    if address is None:
        fail("server never printed its address", proc)
    print(f"[serve-smoke] server up at {address}")

    try:
        health = json.loads(
            urlreq.urlopen(address + "/healthz", timeout=30).read()
        )
        assert health["status"] == "ok", health
        assert health["slots"]["default"]["epoch"] == 0, health
        print(f"[serve-smoke] /healthz ok: {health['slots']}")

        obs = [0.1 * i for i in range(OBS_DIM)]
        req = urlreq.Request(
            address + "/act",
            data=json.dumps({"obs": obs, "deterministic": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        out = json.loads(urlreq.urlopen(req, timeout=30).read())
        assert len(out["action"]) == ACT_DIM, out
        assert all(abs(a) <= 1.0 for a in out["action"]), out
        assert out["generation"] == 0, out
        # determinism across the wire: same obs, same bits
        out2 = json.loads(urlreq.urlopen(req, timeout=30).read())
        assert out2["action"] == out["action"], (out, out2)
        print(f"[serve-smoke] /act ok: {out['action']}")
    except Exception as e:  # noqa: BLE001 — any failure is a smoke fail
        fail(repr(e), proc)
    finally:
        proc.terminate()
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()

    print("[serve-smoke] OK")
    sys.exit(0)


if __name__ == "__main__":
    main()
