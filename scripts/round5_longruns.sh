#!/usr/bin/env bash
# Round-5 long-run chain for the 1-core sandbox (sequential on purpose:
# two CPU-bound trainings would halve each other's throughput).
#
#   1. the 120k-step fused DrQ pixel proof (VERDICT r4 #2) — all-or-
#      nothing artifact, so it gets the core first and longest;
#   2. the 4-seed population HalfCheetah evidence run (VERDICT r4 #1).
#
# Each stage commits its artifact as it lands, so a mid-chain death
# costs only the unfinished stage.
set -u
cd "$(dirname "$0")/.."
export TAC_BENCH_PLATFORM=cpu JAX_PLATFORMS=cpu

echo "[longruns] pixel proof starting at $(date -u +%FT%TZ)"
python scripts/tpu_train_proof.py --task pixel --allow-cpu
rc=$?
echo "[longruns] pixel proof rc=$rc at $(date -u +%FT%TZ)"
# rc 0 = solved, rc 2 = ran to completion but under the solved band —
# both are complete, honest artifacts (the JSON records solved:
# true/false). Anything else is a crash: a partial artifact must NOT
# be committed as if it were the finished proof.
if [ "$rc" -eq 0 ] || [ "$rc" -eq 2 ]; then
    git add runs/train_proof/*.json 2>/dev/null
    git commit -q -m "Pixel train proof: 120k-step fused DrQ run (CPU backend)" \
        -- runs/train_proof 2>/dev/null && echo "[longruns] committed pixel proof"
else
    echo "[longruns] pixel proof CRASHED (rc=$rc); artifact left uncommitted"
fi

echo "[longruns] popcheetah starting at $(date -u +%FT%TZ)"
if python scripts/evidence_run.py popcheetah; then
    git add runs/popcheetah 2>/dev/null
    git commit -q -m "Population evidence: 4-seed HalfCheetah, one vmapped burst" \
        -- runs/popcheetah 2>/dev/null && echo "[longruns] committed popcheetah"
else
    echo "[longruns] popcheetah FAILED"
fi
echo "[longruns] chain done at $(date -u +%FT%TZ)"
