#!/usr/bin/env bash
# Serial evidence-run queue for the 1-core sandbox.
#
# Consumes preset names (one per line) from runs/evidence_queue.txt,
# running each through scripts/evidence_run.py on the CPU backend and
# committing the artifacts as they land.  Lines may be appended while
# the queue is running; the queue exits when the file is empty.
# Start with:
#   nohup bash scripts/evidence_queue.sh >> runs/evidence_queue.log 2>&1 &
# APPEND PROTOCOL: writers must take the same lock as the pop, or an
# append can land between the pop's read and its truncate-replace and
# be lost:
#   flock runs/evidence_queue.txt.lock bash -c \
#     'printf "preset\n" >> runs/evidence_queue.txt'
set -u
cd "$(dirname "$0")/.."
QUEUE=runs/evidence_queue.txt
export JAX_PLATFORMS=cpu

while true; do
    # Never contend with a chip capture: its torch-CPU baseline stage
    # is wall-clock-timed on this same core, and a concurrent evidence
    # run would inflate the vs_baseline ratio.
    while pgrep -f "tpu_capture.py|tpu_smoke.py|tpu_train_proof.py" >/dev/null; do
        echo "[evidence_queue] chip capture in flight; waiting 60s"
        sleep 60
    done
    # Atomic pop under flock: an append racing the read-truncate pair
    # could land between `tail > tmp` and `mv` and be silently lost.
    # The lock closes the race only for writers that follow the APPEND
    # PROTOCOL above (take the same lock); the pop side alone cannot
    # protect an unlocked `>>` from the truncate-replace.
    next=$(
        flock "$QUEUE.lock" bash -c '
            next=$(head -n 1 "'"$QUEUE"'" 2>/dev/null || true)
            if [ -n "$next" ]; then
                tail -n +2 "'"$QUEUE"'" > "'"$QUEUE"'.tmp" \
                    && mv "'"$QUEUE"'.tmp" "'"$QUEUE"'"
            fi
            printf "%s" "$next"
        '
    )
    if [ -z "${next:-}" ]; then
        echo "[evidence_queue] queue empty; exiting at $(date -u +%FT%TZ)"
        break
    fi
    echo "[evidence_queue] running $next at $(date -u +%FT%TZ)"
    if python scripts/evidence_run.py "$next"; then
        git add "runs/$next" 2>/dev/null
        git commit -q -m "Commit regenerated evidence run: $next" \
            -- "runs/$next" 2>/dev/null \
            && echo "[evidence_queue] committed runs/$next"
    else
        echo "[evidence_queue] PRESET FAILED: $next (continuing)"
    fi
done
