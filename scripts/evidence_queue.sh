#!/usr/bin/env bash
# Serial evidence-run queue for the 1-core sandbox.
#
# Consumes preset names (one per line) from runs/evidence_queue.txt,
# running each through scripts/evidence_run.py on the CPU backend and
# committing the artifacts as they land.  Lines may be appended while
# the queue is running; the queue exits when the file is empty.
# Start with:
#   nohup bash scripts/evidence_queue.sh >> runs/evidence_queue.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
QUEUE=runs/evidence_queue.txt
export JAX_PLATFORMS=cpu

while true; do
    next=$(head -n 1 "$QUEUE" 2>/dev/null || true)
    if [ -z "${next:-}" ]; then
        echo "[evidence_queue] queue empty; exiting at $(date -u +%FT%TZ)"
        break
    fi
    # Never contend with a chip capture: its torch-CPU baseline stage
    # is wall-clock-timed on this same core, and a concurrent evidence
    # run would inflate the vs_baseline ratio.
    while pgrep -f "tpu_capture.py|tpu_smoke.py|tpu_train_proof.py" >/dev/null; do
        echo "[evidence_queue] chip capture in flight; waiting 60s"
        sleep 60
    done
    # Consume the line before running so a crash doesn't loop forever.
    tail -n +2 "$QUEUE" > "$QUEUE.tmp" && mv "$QUEUE.tmp" "$QUEUE"
    echo "[evidence_queue] running $next at $(date -u +%FT%TZ)"
    if python scripts/evidence_run.py "$next"; then
        git add "runs/$next" 2>/dev/null
        git commit -q -m "Commit regenerated evidence run: $next" \
            -- "runs/$next" 2>/dev/null \
            && echo "[evidence_queue] committed runs/$next"
    else
        echo "[evidence_queue] PRESET FAILED: $next (continuing)"
    fi
done
