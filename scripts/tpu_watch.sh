#!/bin/bash
# Poll the TPU tunnel continuously; each time it answers, capture chip
# evidence into runs/tpu/ (incremental bench artifact + smoke log).
# Evidence lands in the repo, never /tmp — a tunnel that dies later
# cannot erase it (VERDICT r2 item 1).
#
# Round-5 revision: the 2026-08-02 window showed a new failure mode —
# the tunnel answers but compiles each XLA program in MINUTES, so the
# original fixed per-stage timeouts killed most stages mid-compile.
# The loop now (a) probes faster (windows are short; every probe-cycle
# minute is capture budget), (b) fills ONE round-accumulating artifact
# via scripts/tpu_mopup.py with slow-tunnel timeouts instead of
# restarting a fresh capture per window — the persistent compile cache
# (.jax_cache) makes retries progressive, and (c) commits after every
# completed stage via the mop-up's incremental flush + the commit step
# below.
#
# Run it in the background for a whole working session:
#   tmux new-session -d -s tpuwatch 'bash scripts/tpu_watch.sh'
set -u
cd "$(dirname "$0")/.." || exit 1
mkdir -p runs/tpu
PROBE_SLEEP=120       # between probes while the tunnel is down
REFRESH_SLEEP=1800    # between cycles once the artifact is complete
ARTIFACT="runs/tpu/bench_20260802T154654Z.json"  # round-5 accumulator
i=0
while :; do
    i=$((i + 1))
    if timeout 75 python -c "
import jax, jax.numpy as jnp
assert float((jnp.ones((8,8))@jnp.ones((8,8)))[0,0]) == 8.0
" >/dev/null 2>&1; then
        stamp=$(date -u +%Y%m%dT%H%M%SZ)
        echo "[tpu_watch] probe $i: tunnel alive; capturing ($stamp)"
        # Chip minutes are rare; CPU evidence jobs (the --allow-cpu
        # proof chain, evidence_run presets) would contend with the
        # capture's wall-clock-timed stages (torch baseline!) on this
        # 1-core host. Freeze them for the capture, resume after.
        pkill -STOP -f "allow-cpu|evidence_run.py" 2>/dev/null
        # EXIT alone does not fire on fatal signals (tmux kill-session
        # sends HUP; kill sends TERM) — a dead watch must never leave
        # the multi-hour evidence jobs frozen.
        trap 'pkill -CONT -f "allow-cpu|evidence_run.py" 2>/dev/null' \
            EXIT INT TERM HUP
        # Fill the round artifact's missing stages, cheapest-first so a
        # short window banks the most sections (mop-up flushes + we
        # commit after the whole pass; its per-stage timeouts assume
        # minutes-per-compile). The artifact keeps its original
        # captured_utc; each mop-up stage that lands IS round-5-fresh.
        if [ -f "$ARTIFACT" ]; then
            timeout 14400 python scripts/tpu_mopup.py "$ARTIFACT" \
                2>&1 | tee -a "runs/tpu/mopup_${stamp}.log" | tail -3
        else
            timeout 6600 python scripts/tpu_capture.py 2>&1 \
                | tee "runs/tpu/capture_${stamp}.log" | tail -3
        fi
        git add runs/tpu >/dev/null 2>&1
        git diff --cached --quiet -- runs/tpu || \
            git commit -q -m "Chip evidence: bench stages (${stamp})" -- runs/tpu
        # Pixel proof: visual SAC (DrQ recipe) trained through the
        # fused on-chip-rendered loop, evaluated on the host env —
        # the pixel-learning demonstration the CPU budget cannot
        # reach (PARITY.md "Pixel learning").
        # Bounded retries: the -400 threshold is untested at chip
        # scale, so cap at 3 attempts — failed artifacts are still
        # informative (a 120k-step chip curve) but must not grow the
        # history unboundedly.
        pixel_tries=$(ls runs/tpu/train_proof_pixel_*.json 2>/dev/null | wc -l)
        if [ "$pixel_tries" -lt 3 ] \
           && ! grep -ls '"solved": true' runs/tpu/train_proof_pixel_*.json >/dev/null 2>&1; then
            timeout 7200 python scripts/tpu_train_proof.py --task pixel \
                >"runs/tpu/train_proof_pixel_${stamp}.log" 2>&1
            tail -2 "runs/tpu/train_proof_pixel_${stamp}.log"
        fi
        # First-compile of the smoke's five stages (Mosaic flash bwd,
        # sequence burst) takes >15 min on the tunneled chip; slow
        # windows take longer still.
        if [ ! -f runs/tpu/smoke_r5_ok ]; then
            if timeout 3600 python scripts/tpu_smoke.py \
                    >"runs/tpu/smoke_${stamp}.log" 2>&1; then
                touch runs/tpu/smoke_r5_ok
            fi
            tail -2 "runs/tpu/smoke_${stamp}.log"
        fi
        # One-shot convergence proof (train on chip, eval on host env);
        # a SOLVED proof does not improve with repetition. Only
        # "solved": true satisfies the guard.
        # (train_proof_[0-9]* excludes the pixel artifacts above —
        # each proof family has its own one-shot guard.)
        if ! grep -ls '"solved": true' runs/tpu/train_proof_[0-9]*.json >/dev/null 2>&1; then
            timeout 3600 python scripts/tpu_train_proof.py \
                >"runs/tpu/train_proof_${stamp}.log" 2>&1
            tail -2 "runs/tpu/train_proof_${stamp}.log"
        fi
        # Artifacts must survive even if nobody is around to commit
        # them: commit runs/tpu/ (and only it) right away. The rolling
        # watch.log is gitignored; a no-change cycle commits nothing.
        git add runs/tpu >/dev/null 2>&1
        if ! git diff --cached --quiet -- runs/tpu; then
            git commit -q -m "Record chip evidence captured ${stamp}" -- runs/tpu \
                && echo "[tpu_watch] committed evidence (${stamp})"
        fi
        pkill -CONT -f "allow-cpu|evidence_run.py" 2>/dev/null
        echo "[tpu_watch] capture done; next refresh in ${REFRESH_SLEEP}s"
        sleep "$REFRESH_SLEEP"
    else
        echo "[tpu_watch] probe $i: tunnel down; retry in ${PROBE_SLEEP}s"
        sleep "$PROBE_SLEEP"
    fi
done
