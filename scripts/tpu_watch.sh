#!/bin/bash
# Poll the TPU tunnel; when it answers, run the real-chip smoke and the
# full benchmark, teeing results to /tmp/tpu_recovery_{smoke,bench}.log.
# One-shot: exits after the first successful (or failed) run pair.
set -u
for i in $(seq 1 60); do
    if timeout 75 python -c "
import jax, jax.numpy as jnp
assert float((jnp.ones((8,8))@jnp.ones((8,8)))[0,0]) == 8.0
" >/dev/null 2>&1; then
        echo "[tpu_watch] tunnel alive after $i probes; running smoke+bench"
        timeout 900 python scripts/tpu_smoke.py 2>&1 | tail -12 | tee /tmp/tpu_recovery_smoke.log
        timeout 2400 python bench.py 2>/tmp/tpu_recovery_bench.stderr | tee /tmp/tpu_recovery_bench.log
        echo "[tpu_watch] done"
        exit 0
    fi
    echo "[tpu_watch] probe $i: tunnel still down"
    sleep 300
done
echo "[tpu_watch] gave up after 60 probes"
exit 1
