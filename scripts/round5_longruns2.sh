#!/usr/bin/env bash
# Round-5 follow-on chain: waits for round5_longruns.sh (pixel proof +
# popcheetah) to release the core, then runs the remaining evidence:
#
#   3. sim-to-sim cheetah transfer probe (surrogate-trained policy on
#      real MuJoCo — measures the surrogate gap, VERDICT r4 #5);
#   4. the long wall-runner pool run (VERDICT r4 #6) — LAST because it
#      eats whatever wall-clock remains; its per-epoch metrics.jsonl
#      survives a cutoff, and this chain commits it periodically.
set -u
cd "$(dirname "$0")/.."
export TAC_BENCH_PLATFORM=cpu JAX_PLATFORMS=cpu

# Wait for chain 1's explicit completion marker, not pgrep: a poll
# landing in the gap BETWEEN chain 1's jobs (or before it starts)
# would otherwise start this chain early and halve both jobs'
# throughput on the 1-core host.
while ! grep -q "\[longruns\] chain done" runs/longruns.log 2>/dev/null; do
    sleep 120
done
echo "[longruns2] chain 1 done; cheetah transfer probe at $(date -u +%FT%TZ)"
python scripts/tpu_train_proof.py --task cheetah --allow-cpu
rc=$?
if [ "$rc" -eq 0 ] || [ "$rc" -eq 2 ]; then
    git add runs/train_proof/*.json 2>/dev/null
    git commit -q -m "Cheetah sim-to-sim transfer probe (surrogate -> real MuJoCo eval)" \
        -- runs/train_proof 2>/dev/null && echo "[longruns2] committed cheetah probe"
else
    echo "[longruns2] cheetah probe CRASHED (rc=$rc); not committed"
fi

echo "[longruns2] wallrunner-long starting at $(date -u +%FT%TZ)"
# Periodic committer: the run's value is the trend, which must survive
# a wall-clock cutoff. Commits runs/wallrunner-long every 20 min while
# the training runs.
python scripts/evidence_run.py wallrunner-long &
train_pid=$!
(
    while kill -0 "$train_pid" 2>/dev/null; do
        sleep 1200
        git add runs/wallrunner-long 2>/dev/null
        git commit -q -m "wallrunner-long: periodic metrics snapshot" \
            -- runs/wallrunner-long 2>/dev/null \
            && echo "[longruns2] periodic wallrunner-long commit"
    done
) &
if wait "$train_pid"; then
    git add runs/wallrunner-long 2>/dev/null
    git commit -q -m "Wall-runner long run: parallel pool, committed trend" \
        -- runs/wallrunner-long 2>/dev/null \
        && echo "[longruns2] committed wallrunner-long"
else
    echo "[longruns2] wallrunner-long FAILED (partial metrics may be committed)"
fi
echo "[longruns2] chain done at $(date -u +%FT%TZ)"
