"""Compute-cost attribution smoke: cost events, roofline, one trace.

Runs, on CPU (the tier-1 environment — ``cost_analysis()`` works on
CPU-lowered programs), the whole cost-attribution contract
(docs/OBSERVABILITY.md "Cost attribution & roofline"):

1. a short host-Trainer run with telemetry on → every epoch after the
   first update epoch carries a ``cost`` event whose roofline record
   is present and finite, `cost/` columns land in metrics.jsonl, and
   epoch events carry host/device/input attribution;
2. an in-process serve round (PolicyServer + HTTP /act with an
   ``X-Request-Id``) → ``/metrics`` exposes per-bucket ``costs``
   entries, and the registered per-bucket FLOPs are MONOTONE in the
   bucket size (a bigger batch must cost more);
3. one cross-plane Perfetto export → the file loads as valid JSON,
   timestamps are sorted, and BOTH planes' spans (training phases +
   at least one serve request span) share the timeline.

The ``make cost-smoke`` gate; ~60s on a 2-thread CPU host.
"""

import json
import math
import os
import sys
import tempfile
from pathlib import Path
from urllib import request as urlreq

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fail(msg):
    print(f"[cost-smoke] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def check_finite(record, path):
    for k, v in record.items():
        if isinstance(v, dict):
            check_finite(v, f"{path}.{k}")
        elif isinstance(v, float) and not math.isfinite(v):
            fail(f"non-finite value at {path}.{k}: {v}")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    # Pin the roofline denominators: a host CPU has no device-kind
    # entry, and the classification path must still be exercised.
    os.environ.setdefault("TAC_PEAK_FLOPS", "1e12")
    os.environ.setdefault("TAC_PEAK_BW", "1e11")

    import jax.numpy as jnp

    from torch_actor_critic_tpu.diagnostics.watchdog import get_watchdog
    from torch_actor_critic_tpu.models import Actor
    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.serve import ModelRegistry, PolicyServer
    from torch_actor_critic_tpu.telemetry import (
        RequestSpanLog,
        TelemetryRecorder,
        export_trace,
        get_cost_registry,
    )
    from torch_actor_critic_tpu.telemetry.traceview import (
        compile_events,
        serve_request_events,
        training_events,
    )
    from torch_actor_critic_tpu.utils.config import SACConfig
    from torch_actor_critic_tpu.utils.tracking import Tracker

    # --- 1. training plane: cost events + metrics columns ---
    root = Path(tempfile.mkdtemp(prefix="cost_smoke_"))
    tracker = Tracker(experiment="cost", root=root)
    cfg = SACConfig(
        hidden_sizes=(16, 16), batch_size=16, epochs=2, steps_per_epoch=40,
        start_steps=10, update_after=10, update_every=10, buffer_size=500,
        max_ep_len=100, telemetry=True,
    )
    rec = TelemetryRecorder(run_dir=tracker.run_dir)
    tr = Trainer(
        "Pendulum-v1", cfg, mesh=make_mesh(dp=1), tracker=tracker,
        telemetry=rec,
    )
    try:
        tr.train()
    finally:
        tr.close()

    events = [
        json.loads(line)
        for line in (tracker.run_dir / "telemetry.jsonl").read_text()
        .splitlines()
    ]
    cost_events = [e for e in events if e["type"] == "cost"]
    if len(cost_events) != cfg.epochs:
        fail(f"expected {cfg.epochs} cost events, got {len(cost_events)}")
    for ev in cost_events:
        programs = ev.get("programs") or {}
        if "train/update_burst" not in programs:
            fail(f"cost event missing train/update_burst: {ev}")
        rl = programs["train/update_burst"]
        for key in ("flops_per_call", "bytes_per_call",
                    "achieved_flops_per_sec", "arithmetic_intensity",
                    "mfu", "bound"):
            if key not in rl:
                fail(f"cost record missing {key}: {rl}")
        if rl["flops_per_call"] <= 0 or rl["bytes_per_call"] <= 0:
            fail(f"degenerate cost record: {rl}")
        if rl["bound"] not in ("compute", "memory"):
            fail(f"bad roofline class: {rl['bound']}")
        check_finite(rl, "cost")
    epochs = [e for e in events if e["type"] == "epoch"]
    for ev in epochs:
        attr = ev.get("attribution")
        if not attr or attr["class"] not in (
            "host-bound", "device-bound", "input-bound"
        ):
            fail(f"epoch {ev['epoch']} missing/bad attribution: {attr}")
    rows = [
        json.loads(line)
        for line in (tracker.run_dir / "metrics.jsonl").read_text()
        .splitlines()
    ]
    for row in rows:
        for key in ("cost/update_burst_gflops",
                    "cost/update_burst_achieved_gflops_s",
                    "cost/update_burst_mfu"):
            if key not in row or row[key] is None or row[key] <= 0:
                fail(f"metrics row missing/bad {key}: {row}")
    print(f"[cost-smoke] training plane ok: {len(cost_events)} cost "
          f"events, attribution on {len(epochs)} epochs, cost/ columns "
          "in metrics.jsonl")

    # --- 2. serving plane: /metrics costs + FLOPs monotone in bucket ---
    actor = Actor(act_dim=2, hidden_sizes=(16, 16))
    params = actor.init(
        jax.random.key(0), jnp.zeros((3,)), jax.random.key(1)
    )
    registry = ModelRegistry()
    registry.register(
        "default", actor, jax.ShapeDtypeStruct((3,), jnp.float32),
        params=params, max_batch=8,
    )
    cost_reg = get_cost_registry()
    flops = {}
    for bucket in (2, 4, 8):
        cost = cost_reg.get(f"serve/forward[b{bucket}]")
        if cost is None or cost["flops"] <= 0:
            fail(f"no registered cost for serve/forward[b{bucket}]")
        flops[bucket] = cost["flops"]
    if not (flops[2] < flops[4] < flops[8]):
        fail(f"per-bucket FLOPs not monotone in batch size: {flops}")

    span_log = RequestSpanLog()
    with PolicyServer(
        registry, port=0, max_batch=8, span_log=span_log
    ) as srv:
        srv.start()
        for i in range(6):
            req = urlreq.Request(
                srv.address + "/act",
                data=json.dumps({"obs": [0.1, 0.2, 0.3]}).encode(),
                headers={
                    "Content-Type": "application/json",
                    "X-Request-Id": f"smoke-{i}",
                },
            )
            resp = urlreq.urlopen(req, timeout=30)
            if resp.headers.get("X-Request-Id") != f"smoke-{i}":
                fail("X-Request-Id not echoed on the response")
        snap = json.loads(
            urlreq.urlopen(srv.address + "/metrics", timeout=30).read()
        )
        costs = snap.get("costs") or {}
        if not costs:
            fail(f"/metrics has no costs section: {sorted(snap)}")
        for name, entry in costs.items():
            for key in ("flops_per_call", "achieved_flops_per_sec",
                        "mfu", "bound"):
                if key not in entry:
                    fail(f"/metrics costs[{name}] missing {key}: {entry}")
            check_finite(entry, f"costs.{name}")
    print(f"[cost-smoke] serving plane ok: /metrics costs for "
          f"{sorted(costs)}, FLOPs monotone over buckets {sorted(flops)}")

    # --- 3. cross-plane trace export ---
    trace_path = root / "trace.json"
    summary = export_trace(
        trace_path,
        training_events(rec),
        serve_request_events(span_log.records()),
        compile_events(get_watchdog().compile_log()),
    )
    trace = json.loads(trace_path.read_text())  # valid JSON or dies
    span_events = [
        e for e in trace["traceEvents"] if e.get("ph") in ("B", "E")
    ]
    ts = [e["ts"] for e in span_events]
    if ts != sorted(ts):
        fail("trace events not sorted by timestamp")
    if summary["train_spans"] == 0:
        fail("trace has no training phase spans")
    if summary["serve_spans"] == 0:
        fail("trace has no serve request spans")
    names = {e["name"] for e in span_events}
    if "request" not in names or "act" not in names:
        fail(f"expected both planes' span names in trace, got {names}")
    print(f"[cost-smoke] trace ok: {summary['train_spans']} train + "
          f"{summary['serve_spans']} serve + {summary['compile_spans']} "
          f"compile spans in {trace_path}")
    print("[cost-smoke] PASS")


if __name__ == "__main__":
    main()
