"""Incremental real-chip benchmark capture.

Run by ``scripts/tpu_watch.sh`` whenever the TPU tunnel answers (and
manually any time). Unlike ``bench.py`` — which emits one JSON line for
the driver at round end — this writes a timestamped artifact under
``runs/tpu/`` and REWRITES it after every completed stage, so a tunnel
that dies mid-capture still leaves every stage that finished on disk
(VERDICT r2 item 1: chip evidence must survive a flaky tunnel).

The artifact shape matches ``bench.py``'s output, so a later CPU-backed
``bench.py`` run surfaces it verbatim as ``last_known_tpu``.

Usage: ``python scripts/tpu_capture.py`` (stages reuse bench.py's
subprocess isolation — a hang loses one stage, not the capture).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main() -> int:
    info, pf_diags = bench.preflight_backend()
    if info.get("platform") in (None, "none", "cpu"):
        print(f"no accelerator backend ({info}); nothing to capture")
        return 1

    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    os.makedirs(bench.TPU_EVIDENCE_DIR, exist_ok=True)
    path = os.path.join(bench.TPU_EVIDENCE_DIR, f"bench_{stamp}.json")
    out = {
        "metric": "sac_grad_steps_per_sec",
        "value": None,
        "unit": "steps/sec",
        "vs_baseline": None,
        "backend": info.get("platform"),
        "device_kind": info.get("device_kind"),
        "captured_utc": stamp,
        "capture": "incremental (scripts/tpu_capture.py)",
    }
    diagnostics: list = []

    def flush():
        # Diagnostics ride along on EVERY flush: if the watch loop's
        # outer timeout kills this process mid-capture, the artifact
        # still records which stages failed and why.
        if diagnostics:
            out["capture_diagnostics"] = diagnostics
        with open(path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)

    flush()
    platform = info.get("platform")

    # Headline first: the one number that matters most lands on disk
    # before anything slower gets a chance to hang. MFU/baseline keys
    # come from bench.py's shared helpers so these artifacts can never
    # drift from the driver's JSON lines.
    res = bench.run_stage_subprocess("headline", 600, diagnostics, platform)
    if res and "acc_sps" in res:
        sps = res["acc_sps"]
        out["value"] = round(sps, 1)
        out.update(bench.mfu_metrics(sps, info.get("device_kind")))
        torch_sps, torch_keys = bench.torch_baseline_metrics(diagnostics)
        out.update(torch_keys)
        out["vs_baseline"] = round(sps / torch_sps, 2)
    elif res:
        diagnostics.append({"headline_error": res.get("error")})
    flush()
    print(f"[capture] headline: {out['value']} steps/s -> {path}", flush=True)

    for stage, timeout_s in (
        ("headline_bf16", 600),
        ("sweep", 900),
        ("unroll", 420),
        ("td3", 420),
        ("population", 600),  # round-5: N-seed vmapped burst scaling
        ("visual", 480),
        ("serving", 420),  # serve/ micro-batched inference fan-out
        ("on_device", 540),
        ("attention", 1200),
    ):
        res = bench.run_stage_subprocess(stage, timeout_s, diagnostics, platform)
        if res and "acc_sps_bf16" in res:
            out["value_bf16"] = round(res.pop("acc_sps_bf16"), 1)
        if res and "error" in res:
            diagnostics.append({f"{stage}_error": res.pop("error")})
        if res:
            out.update(res)
        flush()
        print(f"[capture] {stage} done", flush=True)

    flush()
    print(f"[capture] complete: {path}", flush=True)
    return 0 if out["value"] is not None else 2


if __name__ == "__main__":
    sys.exit(main())
