"""Sharded-serving smoke: 2 workers x 2 sub-mesh replicas, kill+reload.

End-to-end proof of docs/SERVING.md "Sharded serving & precision
tiers" through the REAL operator entry point — ``serve.py --devices
all --submesh 2x2 --fleet 2`` under the forced 8-device CPU shim (each
worker process carves its 8 virtual devices into two (2,2) sub-mesh
replicas; the router fronts the two workers), ~2 min:

1. **Flood + mid-flood validated hot-reload**: a closed-loop client
   herd floods the router; MID-flood a newer checkpoint epoch is
   written and ``POST /reload`` rolls it across the fleet. Asserts
   every request is answered (zero accepted-request drops), post-roll
   traffic serves the new generation, and the aggregated
   ``reload_transfer_bytes_total`` counter grew by exactly one sharded
   placement per live sub-mesh replica — the one-transfer-per-device
   contract, observed through /metrics.
2. **Mid-flood worker SIGKILL**: one worker dies under load; the
   router fails in-flight proxies over and membership ejects it —
   still zero drops, goodput continues on the surviving worker's two
   sub-meshes.
3. **Teardown**: SIGTERM drains the fleet gracefully, exit 0.

Exits nonzero on any violated invariant; prints a one-line JSON
summary for CI logs.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path
from urllib import request as urlreq

REPO = str(Path(__file__).resolve().parent.parent)
sys.path.insert(0, REPO)
OBS_DIM, ACT_DIM = 17, 6


def fail(msg, proc=None):
    print(f"[shard-serve-smoke] FAIL: {msg}", file=sys.stderr)
    if proc is not None:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=10)
            if out:
                print(out[-3000:], file=sys.stderr)
        except subprocess.TimeoutExpired:
            proc.kill()
    sys.exit(1)


def router_metrics(router):
    return json.loads(urlreq.urlopen(router + "/metrics", timeout=30).read())


def main():
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.serve import PolicyClient
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    tmp = tempfile.mkdtemp(prefix="shard_serve_smoke_")
    ckpt_dir = os.path.join(tmp, "ckpts")
    cfg = SACConfig(hidden_sizes=(32, 32))
    sac = SAC(
        cfg,
        Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32)),
        DoubleCritic(hidden_sizes=(32, 32)),
        ACT_DIM,
    )

    def save_epoch(epoch, seed):
        ck = Checkpointer(ckpt_dir, save_buffer=False)
        try:
            ck.save(
                epoch,
                sac.init_state(jax.random.key(seed), jnp.zeros((OBS_DIM,))),
                extra={"config": cfg.to_json()}, wait=True,
            )
        finally:
            ck.close()

    save_epoch(0, seed=0)
    print(f"[shard-serve-smoke] checkpoint written: {ckpt_dir}")

    # The forced multi-device shim MUST reach the worker processes
    # before their first jax import: 8 virtual CPU devices -> two
    # (2,2) sub-mesh replicas per worker.
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS=(
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip(),
        PYTHONPATH=REPO + (
            os.pathsep + os.environ["PYTHONPATH"]
            if os.environ.get("PYTHONPATH") else ""
        ),
        PALLAS_AXON_POOL_IPS="",
    )
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "serve.py"),
            "--fleet", "2", "--port", "0",
            "--ckpt-dir", ckpt_dir,
            "--obs-dim", str(OBS_DIM), "--act-dim", str(ACT_DIM),
            "--devices", "all", "--submesh", "2x2",
            "--max-batch", "8", "--max-wait-ms", "1",
            "--poll-interval", "0",  # reload only via the explicit roll
            "--router-poll", "0.5",
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )

    info, deadline = None, time.time() + 300
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                fail(f"fleet exited rc={proc.returncode} before ready", proc)
            time.sleep(0.1)
            continue
        sys.stderr.write("[fleet] " + line)
        if line.startswith("{") and '"router"' in line:
            try:
                info = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    if info is None:
        fail("fleet never printed its router address", proc)
    router = info["router"]
    pids = info["pids"]
    assert len(pids) == 2, info
    print(f"[shard-serve-smoke] up: router {router}, worker pids {pids}")
    threading.Thread(
        target=lambda: [None for _ in proc.stdout], daemon=True
    ).start()

    summary = {}
    try:
        # Preflight: both workers expose the sharding section.
        for name, addr in info["workers"].items():
            snap = router_metrics(addr)
            sh = snap.get("sharding")
            if not sh or sh["submesh"] != {"tp": 2, "fsdp": 2}:
                fail(f"worker {name} has no 2x2 sharding section: {sh}")
            if sh["replicas"] != 2:
                fail(f"worker {name} replicas {sh['replicas']} != 2")
        placements0 = router_metrics(router)["param_placements_total"]
        bytes0 = router_metrics(router)["reload_transfer_bytes_total"]
        if placements0 <= 0 or bytes0 <= 0:
            fail(
                f"warmup placed nothing? placements={placements0} "
                f"bytes={bytes0}"
            )

        obs = np.linspace(-1, 1, OBS_DIM).astype(np.float32)
        n_threads, per_thread = 6, 50
        reload_after, kill_after = 40, 140
        answered, errors = [0], []
        count_lock = threading.Lock()
        reloaded, killed = threading.Event(), threading.Event()
        roll_result = {}

        def do_roll():
            save_epoch(1, seed=7)
            req = urlreq.Request(
                router + "/reload", data=b"{}",
                headers={"Content-Type": "application/json"},
            )
            roll_result.update(json.loads(
                urlreq.urlopen(req, timeout=120).read()
            )["reload"])
            print(f"[shard-serve-smoke] mid-flood roll: {roll_result}")

        def flooder(i):
            client = PolicyClient(url=router, retries=3, backoff_s=0.1)
            local_obs = obs + 0.01 * i
            for _ in range(per_thread):
                try:
                    res = client.act(local_obs, timeout=60.0)
                    assert len(res.action) == ACT_DIM
                    with count_lock:
                        answered[0] += 1
                        n = answered[0]
                    if n >= reload_after and not reloaded.is_set():
                        reloaded.set()
                        threading.Thread(
                            target=do_roll, daemon=True
                        ).start()
                    if n >= kill_after and not killed.is_set():
                        killed.set()
                        os.kill(pids[0], signal.SIGKILL)
                        print(
                            f"[shard-serve-smoke] SIGKILLed worker "
                            f"{pids[0]} after {n} responses"
                        )
                except Exception as e:  # noqa: BLE001 — any client
                    # failure is an accepted-request drop: smoke fail
                    errors.append(repr(e)[:300])

        t0 = time.perf_counter()
        herd = [
            threading.Thread(target=flooder, args=(i,))
            for i in range(n_threads)
        ]
        for th in herd:
            th.start()
        for th in herd:
            th.join(timeout=600.0)
        flood_s = time.perf_counter() - t0
        offered = n_threads * per_thread
        if errors:
            fail(f"{len(errors)} dropped/errored requests: {errors[:3]}")
        if answered[0] != offered:
            fail(f"answered {answered[0]} != offered {offered}")
        if not (reloaded.is_set() and killed.is_set()):
            fail("flood ended before reload+kill fired; raise per_thread")

        deadline = time.time() + 60
        while time.time() < deadline:
            health = json.loads(
                urlreq.urlopen(router + "/healthz", timeout=30).read()
            )
            if health["admitted_workers"] == 1:
                break
            time.sleep(0.5)
        else:
            fail(f"membership never ejected the dead worker: {health}")

        # The roll runs concurrently with the flood; wait for it.
        deadline = time.time() + 120
        while time.time() < deadline and not roll_result:
            time.sleep(0.5)
        if not roll_result:
            fail("mid-flood /reload never completed")

        # Post-roll traffic serves the new generation.
        client = PolicyClient(url=router, retries=3)
        res = client.act(obs, timeout=60.0)
        if res.generation != 1:
            fail(f"post-roll generation {res.generation} != 1")
        if res.epoch != 1:
            fail(f"post-roll epoch {res.epoch} != 1")
        for _ in range(8):  # touch both surviving sub-mesh replicas
            client.act(obs, timeout=60.0)

        # One sharded placement per live sub-mesh replica for the
        # reload: the surviving worker's 2 replicas each transferred
        # once more, and each placement moved the same bytes as its
        # initial one (the aggregate only sums LIVE workers — the dead
        # one no longer reports).
        deadline = time.time() + 60
        while time.time() < deadline:
            snap = router_metrics(router)
            if snap.get("param_placements_total") == 4:
                break
            client.act(obs, timeout=60.0)
            time.sleep(0.5)
        sh = snap.get("workers", {})
        live = [w for w in sh.values() if not w.get("unreachable")]
        if len(live) != 1:
            fail(f"expected 1 live worker in /metrics, got {sh}")
        per_bytes = bytes0 // 4  # 2 workers x 2 replicas warmed equally
        got = snap["reload_transfer_bytes_total"]
        if got != 4 * per_bytes:
            fail(
                f"transfer accounting off: live-worker bytes {got} "
                f"!= 4 x {per_bytes} (2 replicas x initial+reload)"
            )
        if snap["param_placements_total"] != 4:
            fail(
                "live worker placements "
                f"{snap['param_placements_total']} != 4 "
                "(2 replicas x initial+reload)"
            )

        summary["flood"] = {
            "offered": offered,
            "answered": answered[0],
            "errors": 0,
            "goodput_rps": round(offered / flood_s, 1),
            "post_roll_generation": res.generation,
            "admitted_workers": health["admitted_workers"],
            "live_worker_placements": snap["param_placements_total"],
            "live_worker_transfer_bytes": got,
        }
        print(f"[shard-serve-smoke] flood ok: {summary['flood']}")

        # ------------------------------------------------ teardown
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            fail("fleet did not exit within 120s of SIGTERM", proc)
        if rc != 0:
            fail(f"fleet exited rc={rc} after graceful SIGTERM")
        summary["teardown"] = {"rc": rc}
    finally:
        if proc.poll() is None:
            proc.kill()

    print("SHARD-SERVE-SMOKE OK " + json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
