"""Cold-start smoke: warm-start bundles through the real serve.py CLI.

The `make coldstart-smoke` gate for the aot/ subsystem
(docs/SERVING.md "Cold start & warm-start bundles"). Writes a real
TrainState checkpoint, builds a warm-start bundle next to it
(aot/bundle.py — jax.export programs + pre-populated persistent
compilation cache), then proves four claims against fresh
``python serve.py`` subprocesses over loopback HTTP:

1. **Cold baseline**: a worker without the bundle comes up, pays its
   compiles inside warmup (``warmup_compiles > 0``), and answers /act.
2. **Warm worker**: ``--warm-start auto`` resolves the
   checkpoint-adjacent bundle; the first /act is answered with ZERO
   serve-plane live compiles (``live_compiles == 0``,
   ``bundle_compiles > 0``, ``warmup_compiles == 0``) and the
   watchdog's three-way split shows the compiles under
   ``bundle_load`` with ``bundle_hits`` counted.
3. **Flood**: a second warm worker (its xla_cache now fully
   populated — ``cache_hits > 0``) takes a chaos-smoke-style
   closed-loop herd flood of deterministic + sampled /act requests
   and HOLDS ``live_compiles == 0`` through all of it.
4. **Tamper rejection**: a fingerprint-corrupted bundle is LOUDLY
   rejected (``bundle_rejected`` bumped), the worker falls back to a
   plain live warmup and still serves correctly.

Also reports time-to-first-act cold vs warm. Runs on CPU in ~1 min;
exits nonzero on any violated invariant.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from urllib import request as urlreq

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBS_DIM, ACT_DIM = 17, 6
MAX_BATCH = 8


def fail(msg, proc=None):
    print(f"[coldstart-smoke] FAIL: {msg}", file=sys.stderr)
    if proc is not None:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=10)
            print(out[-3000:], file=sys.stderr)
        except subprocess.TimeoutExpired:
            proc.kill()
    sys.exit(1)


class Worker:
    """One fresh serve.py subprocess; times spawn -> ready -> first act."""

    def __init__(self, ckpt_dir, extra, label):
        self.label = label
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO + (
                os.pathsep + os.environ["PYTHONPATH"]
                if os.environ.get("PYTHONPATH") else ""
            ),
            PALLAS_AXON_POOL_IPS="",  # accelerator hooks stay out
        )
        self.t_spawn = time.time()
        self.proc = subprocess.Popen(
            [
                sys.executable, os.path.join(REPO, "serve.py"),
                "--ckpt-dir", ckpt_dir,
                "--obs-dim", str(OBS_DIM), "--act-dim", str(ACT_DIM),
                "--port", "0", "--max-batch", str(MAX_BATCH),
                "--max-wait-ms", "2",
            ] + extra,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        self.address = None
        deadline = time.time() + 180
        while time.time() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                if self.proc.poll() is not None:
                    fail(f"{label}: worker died rc={self.proc.returncode}",
                         self.proc)
                time.sleep(0.05)
                continue
            sys.stderr.write(f"[{label}] {line}")
            if line.startswith("{"):
                try:
                    self.startup = json.loads(line)
                    self.address = self.startup["serving"]
                    break
                except (json.JSONDecodeError, KeyError):
                    continue
        if self.address is None:
            fail(f"{label}: worker never printed its address", self.proc)
        self.ready_ms = (time.time() - self.t_spawn) * 1e3
        # Keep the pipe drained so the worker never blocks on stdout.
        threading.Thread(
            target=lambda: [None for _ in self.proc.stdout], daemon=True
        ).start()

    def act(self, deterministic=True, timeout=60):
        req = urlreq.Request(
            self.address + "/act",
            data=json.dumps({
                "obs": [0.1] * OBS_DIM, "deterministic": deterministic,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        out = json.loads(urlreq.urlopen(req, timeout=timeout).read())
        assert len(out["action"]) == ACT_DIM, out
        return out

    def metrics(self):
        return json.loads(
            urlreq.urlopen(self.address + "/metrics", timeout=30).read()
        )

    def health(self):
        return json.loads(
            urlreq.urlopen(self.address + "/healthz", timeout=30).read()
        )

    def close(self):
        self.proc.terminate()
        try:
            self.proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            self.proc.kill()


def main():
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from torch_actor_critic_tpu.aot import default_bundle_dir, emit_bundle
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    summary = {}
    tmp = tempfile.mkdtemp(prefix="coldstart_smoke_")
    ckpt_dir = os.path.join(tmp, "ckpts")
    cfg = SACConfig(hidden_sizes=(32, 32))
    sac = SAC(
        cfg,
        Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32)),
        DoubleCritic(hidden_sizes=(32, 32)),
        ACT_DIM,
    )
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    ck = Checkpointer(ckpt_dir, save_buffer=False)
    ck.save(0, state, extra={"config": cfg.to_json()}, wait=True)
    ck.close()

    t0 = time.time()
    bundle = emit_bundle(
        ckpt_dir, sac.actor_def,
        jax.ShapeDtypeStruct((OBS_DIM,), jnp.float32),
        jax.device_get(state.actor_params), max_batch=MAX_BATCH,
    )
    bundle_dir = str(bundle.root)
    summary["bundle_build_s"] = round(time.time() - t0, 2)
    assert bundle_dir == str(default_bundle_dir(ckpt_dir)), bundle_dir
    print(f"[coldstart-smoke] bundle built at {bundle_dir} "
          f"({summary['bundle_build_s']}s)")

    # ---------------------------------------------- 1. cold baseline
    w = Worker(ckpt_dir, [], "cold")
    try:
        w.act()
        cold_ms = (time.time() - w.t_spawn) * 1e3
        met = w.metrics()
        assert met["live_compiles"] == 0, met["live_compiles"]
        assert met["bundle_compiles"] == 0, met["bundle_compiles"]
        assert met["xla"]["warmup_compiles"] > 0, met["xla"]
        assert w.health()["slots"]["default"]["bundle_loaded"] is False
    finally:
        w.close()
    summary["cold"] = {"first_act_ms": round(cold_ms, 1)}
    print(f"[coldstart-smoke] cold worker ok: first act {cold_ms:.0f}ms")

    # -------------------------------- 2. warm worker, zero live compiles
    w = Worker(ckpt_dir, ["--warm-start", "auto"], "warm")
    try:
        w.act(deterministic=True)
        w.act(deterministic=False)
        warm_ms = (time.time() - w.t_spawn) * 1e3
        met = w.metrics()
        xla = met["xla"]
        assert met["live_compiles"] == 0, met["live_compiles"]
        assert met["bundle_compiles"] > 0, met["bundle_compiles"]
        assert xla["warmup_compiles"] == 0, xla
        assert xla["bundle_load_compiles"] > 0, xla
        assert xla["bundle_hits"] > 0, xla
        assert xla["bundle_rejected"] == 0, xla
        assert w.health()["slots"]["default"]["bundle_loaded"] is True
    finally:
        w.close()
    summary["warm"] = {
        "first_act_ms": round(warm_ms, 1),
        "bundle_compiles": met["bundle_compiles"],
        "bundle_hits": xla["bundle_hits"],
    }
    print(f"[coldstart-smoke] warm worker ok: first act {warm_ms:.0f}ms, "
          f"{met['bundle_compiles']} bundle-armed dispatches, 0 live")

    # ------------------- 3. second warm worker: cache hits, then flood
    w = Worker(ckpt_dir, ["--warm-start", "auto"], "flood")
    try:
        w.act()
        met = w.metrics()
        assert met["xla"]["cache_hits_total"] > 0, met["xla"]
        # chaos-smoke-style closed-loop herd: 8 threads x 100 requests,
        # deterministic and sampled mixed, against the warm worker.
        errors = []

        def herd(n=100):
            for i in range(n):
                try:
                    w.act(deterministic=(i % 2 == 0))
                except Exception as e:  # noqa: BLE001 — collected below
                    errors.append(repr(e))

        threads = [threading.Thread(target=herd) for _ in range(8)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        flood_s = time.time() - t0
        assert not errors, errors[:3]
        met = w.metrics()
        assert met["live_compiles"] == 0, (
            f"flood paid {met['live_compiles']} live compiles"
        )
        assert met["responses_total"] >= 800, met["responses_total"]
    finally:
        w.close()
    summary["flood"] = {
        "requests": 800,
        "seconds": round(flood_s, 1),
        "live_compiles": met["live_compiles"],
        "cache_hits": met["xla"]["cache_hits_total"],
    }
    print(f"[coldstart-smoke] flood ok: 800 acts in {flood_s:.1f}s, "
          f"live_compiles still 0")

    # --------------------------- 4. tampered bundle: loud rejection
    manifest_path = os.path.join(bundle_dir, "MANIFEST.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    manifest["fingerprint"]["jaxlib"] = "0.0.0-tampered"
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    w = Worker(ckpt_dir, ["--warm-start", "auto"], "tampered")
    try:
        w.act()
        met = w.metrics()
        xla = met["xla"]
        assert xla["bundle_rejected"] >= 1, xla
        assert met["bundle_compiles"] == 0, met["bundle_compiles"]
        assert xla["warmup_compiles"] > 0, xla  # fell back to live warmup
        assert met["live_compiles"] == 0, met["live_compiles"]
        assert w.health()["slots"]["default"]["bundle_loaded"] is False
    finally:
        w.close()
    summary["tamper"] = {
        "bundle_rejected": xla["bundle_rejected"],
        "fell_back_to_warmup": True,
    }
    print("[coldstart-smoke] tampered bundle rejected loudly; "
          "worker fell back and served")

    summary["speedup"] = round(
        summary["cold"]["first_act_ms"] / summary["warm"]["first_act_ms"], 2
    )
    print("COLDSTART-SMOKE OK " + json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
