"""Re-run bench stages that timed out during an incremental capture.

The 2026-08-02 tunnel window compiles each XLA program in minutes —
slow enough that `scripts/tpu_capture.py`'s per-stage timeouts (sized
for the 2026-07-31 window) kill most stages mid-compile. Retries are
progressive thanks to the persistent compilation cache (`.jax_cache`,
wired in ``bench.run_stage_subprocess``): every completed compile is
reused, so a stage that timed out resumes where it died.

Usage: ``python scripts/tpu_mopup.py <artifact.json> [stage ...]``
(default stages = every stage the artifact is missing). Merges each
stage's result into the artifact and rewrites it after every stage,
same contract as tpu_capture.py.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

# Stage -> (result key in the artifact, generous timeout). Timeouts are
# sized for minutes-per-compile tunnel latency, not the happy path.
# Order = bank-the-most-value-first for short tunnel windows: the
# VERDICT-named rows (td3, population, visual, attention) and the cheap
# stages before the 10-point MFU sweep.
STAGES = {
    "td3": ("td3", 1800),
    "population": ("population", 2400),
    "unroll": ("burst_unroll", 1800),
    "visual": ("visual", 2400),
    "on_device": ("on_device", 2400),
    "attention": ("attention", 3600),
    "sweep": ("sweep", 2700),
}


def main() -> int:
    path = sys.argv[1]
    with open(path) as f:
        out = json.load(f)

    requested = sys.argv[2:] or [
        s for s, (key, _) in STAGES.items() if key not in out
    ]
    info, _ = bench.preflight_backend()
    if info.get("platform") in (None, "none", "cpu"):
        print(f"no accelerator ({info}); aborting")
        return 1
    platform = info.get("platform")

    diagnostics = [
        d for d in out.get("capture_diagnostics", [])
        # Drop stale timeout records for stages we are about to retry.
        if not any(k.startswith(tuple(requested)) for k in d)
    ]

    def flush():
        out["capture_diagnostics"] = diagnostics
        if not diagnostics:
            out.pop("capture_diagnostics", None)
        with open(path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)

    for stage in requested:
        key, timeout_s = STAGES[stage]
        print(f"[mopup] {stage} (timeout {timeout_s}s)...", flush=True)
        res = bench.run_stage_subprocess(stage, timeout_s, diagnostics, platform)
        if res and "acc_sps_bf16" in res:
            out["value_bf16"] = round(res.pop("acc_sps_bf16"), 1)
        if res and "error" in res:
            diagnostics.append({f"{stage}_error": res.pop("error")})
        if res:
            out.update(res)
        flush()
        print(f"[mopup] {stage} {'ok' if res else 'FAILED'}", flush=True)

    print(f"[mopup] complete -> {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
