#!/usr/bin/env python
"""Run-wide observability smoke (``make obs-smoke``).

Proves the PR-19 obs plane end-to-end with REAL processes
(docs/OBSERVABILITY.md "Run-wide plane"):

1. a serving fleet comes up (``serve.py --fleet 2`` — 2 workers behind
   the router) on a checkpoint built in-process;
2. a fleet learner (``train.py --actors 2 --obs true``) starts with the
   run-scoped ObsCollector scraping three planes: its own learner
   source, the staging transport (``/metrics`` + ``/healthz``), and the
   serving router (``--obs-scrape serve=...``);
3. an SLO choreography drives the serving-goodput rule through its full
   hysteresis cycle: flood the router's ``/act`` (the rule ARMS on
   first pass), stop (windowed rate decays to 0 → exactly one
   ``slo_breach``), flood again (exactly one ``slo_recovered``) — all
   observed live off the collector's own ``/metrics`` endpoint;
4. the learner gets SIGTERM; the exported Perfetto timeline must stitch
   the SAME staging span id (``a<actor>.<inc>.<seq>``) across >= 3
   process lanes: an actor's ``stage_push``, the transport's
   ``stage_ingest``, and the learner's ``drain_window`` tag list.

Asserted at the end: all three obs sources live with ZERO scrape
failures, the ``obs/`` columns in metrics.jsonl, the obs.jsonl series,
exactly one breach + one recovery in telemetry.jsonl, and the
cross-pid span stitch.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request as urlreq
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

OBS_DIM = 3   # Pendulum-v1
ACT_DIM = 1


def log(msg):
    print(f"[obs-smoke] {msg}", flush=True)


def fail(msg):
    log(f"FAIL: {msg}")
    sys.exit(1)


def wait_for(predicate, what, timeout_s=300.0, poll_s=0.25):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(poll_s)
    fail(f"timed out after {timeout_s:.0f}s waiting for {what}")


def free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def get_json(url, timeout=3):
    try:
        with urlreq.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:  # noqa: BLE001 - polling probe
        return None


def jsonl(path: Path):
    if not path.exists():
        return []
    out = []
    for line in path.read_text().splitlines():
        try:
            out.append(json.loads(line))
        except ValueError:
            pass
    return out


def build_checkpoint(ckpt_dir):
    """A serve-able SAC checkpoint without a training run."""
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(hidden_sizes=(16, 16))
    sac = SAC(
        cfg, Actor(act_dim=ACT_DIM, hidden_sizes=(16, 16)),
        DoubleCritic(hidden_sizes=(16, 16)), ACT_DIM,
    )
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    ck = Checkpointer(ckpt_dir, save_buffer=False)
    ck.save(0, state, extra={"config": cfg.to_json()}, wait=True)
    ck.close()


def start_fleet(ckpt_dir, env):
    """serve.py --fleet 2; returns (proc, router_url)."""
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "serve.py"),
         "--ckpt-dir", ckpt_dir,
         "--obs-dim", str(OBS_DIM), "--act-dim", str(ACT_DIM),
         "--fleet", "2", "--port", "0", "--router-poll", "0.5",
         "--max-batch", "4", "--max-wait-ms", "2",
         "--poll-interval", "0"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    router = None
    deadline = time.time() + 300
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                fail(f"fleet died rc={proc.returncode} before ready")
            time.sleep(0.1)
            continue
        sys.stderr.write(f"[fleet] {line}")
        if line.startswith("{"):
            try:
                router = json.loads(line)["router"]
                break
            except (json.JSONDecodeError, KeyError):
                continue
    if router is None:
        fail("the fleet never printed its router address")
    threading.Thread(
        target=lambda: [None for _ in proc.stdout], daemon=True
    ).start()
    return proc, router


def main():
    tmp = Path(tempfile.mkdtemp(prefix="obs_smoke_"))
    ckpt_dir = str(tmp / "ckpts")
    runs_root = tmp / "runs"
    trace_path = tmp / "trace.json"
    obs_port = free_port()
    fleet_port = free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    log("building a serve-able checkpoint ...")
    build_checkpoint(ckpt_dir)

    # SLO config: ONE rule, on the serving plane's windowed goodput.
    # Arm-on-first-pass means nothing fires until the flood starts.
    slo_path = tmp / "slo.json"
    slo_path.write_text(json.dumps([{
        "name": "serve_goodput", "path": "serve.requests_per_sec",
        "op": "min", "threshold": 0.5,
        "breach_windows": 2, "recover_windows": 2,
    }]))

    log("phase 1: serving fleet (2 workers + router) ...")
    fleet, router = start_fleet(ckpt_dir, env)
    learner = None
    flood_stop = threading.Event()
    flood_on = threading.Event()
    try:
        wait_for(
            lambda: (m := get_json(router + "/metrics")) is not None
            and m.get("workers_reporting") == 2,
            "both fleet workers behind the router",
        )

        def flood():
            body = json.dumps(
                {"obs": [0.1] * OBS_DIM, "deterministic": True}
            ).encode()
            while not flood_stop.is_set():
                if not flood_on.is_set():
                    time.sleep(0.05)
                    continue
                try:
                    req = urlreq.Request(
                        router + "/act", data=body,
                        headers={"Content-Type": "application/json"},
                    )
                    urlreq.urlopen(req, timeout=10).read()
                except Exception:  # noqa: BLE001 - flood is best effort
                    time.sleep(0.1)

        for _ in range(2):
            threading.Thread(target=flood, daemon=True).start()

        log("phase 2: fleet learner with --obs (3 planes) ...")
        learner = subprocess.Popen(
            [sys.executable, "-m", "torch_actor_critic_tpu.train",
             "--environment", "Pendulum-v1",
             "--hidden-sizes", "16,16", "--batch-size", "16",
             "--epochs", "60", "--steps-per-epoch", "200",
             "--start-steps", "20", "--update-after", "20",
             "--update-every", "20", "--buffer-size", "2000",
             "--max-ep-len", "200",
             "--decoupled", "true", "--actors", "2",
             "--fleet-port", str(fleet_port),
             "--telemetry", "true",
             "--obs", "true",
             "--obs-interval-s", "0.5",
             "--obs-port", str(obs_port),
             "--obs-scrape", f"serve={router}",
             "--slo-config", str(slo_path),
             "--trace-export", str(trace_path),
             "--runs-root", str(runs_root), "--experiment", "obs"],
            cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        obs_url = f"http://127.0.0.1:{obs_port}"

        def obs_metrics():
            return get_json(obs_url + "/metrics")

        def rule_state():
            m = obs_metrics()
            if m is None:
                return None
            return m["slo"]["rules"]["serve_goodput"]

        wait_for(
            lambda: obs_metrics() is not None,
            "the obs collector's /metrics endpoint",
        )

        log("phase 3: SLO choreography — flood (arm) ...")
        flood_on.set()
        wait_for(
            lambda: (st := rule_state()) is not None and st["armed"],
            "the serve_goodput rule to arm",
        )

        log("phase 3: stop the flood (breach) ...")
        flood_on.clear()
        wait_for(
            lambda: (st := rule_state()) is not None and st["breached"],
            "the slo_breach",
        )

        log("phase 3: flood again (recover) ...")
        flood_on.set()
        wait_for(
            lambda: (st := rule_state()) is not None
            and not st["breached"] and st["recoveries_total"] >= 1,
            "the slo_recovered",
        )
        # Keep the flood running until the learner has exited: clearing
        # it here would let the windowed serve rate decay to 0 again and
        # (correctly) fire a SECOND breach during the remaining scrape
        # windows — the exactly-once assertion below counts episodes,
        # and we only choreographed one.

        # Aggregation health: all three planes live, zero failures.
        m = wait_for(obs_metrics, "a final obs snapshot")
        for name in ("learner", "fleet", "serve"):
            if name not in m["sources"]:
                fail(f"obs source {name!r} missing: {sorted(m['sources'])}")
            if not m["sources"][name]["live"]:
                fail(f"obs source {name!r} not live: {m['sources'][name]}")
        if m["scrape_failed_total"] != 0:
            fail(f"scrape failures: {m['scrape_failed_total']} "
                 f"({ {n: s.get('last_error') for n, s in m['sources'].items()} })")
        if m["last"]["fleet"]["healthz"]["conservation_ok"] is not True:
            fail("transport /healthz conservation probe not ok")
        st = rule_state()
        if st["breaches_total"] != 1 or st["recoveries_total"] != 1:
            fail(f"expected exactly one breach + one recovery, got {st}")
        log(f"obs plane healthy: sources={sorted(m['sources'])} "
            f"scrapes={m['scrapes_total']} failures=0 "
            f"breaches={st['breaches_total']} "
            f"recoveries={st['recoveries_total']}")

        # At least one epoch must have landed so metrics.jsonl carries
        # the obs/ columns.
        run_dir = wait_for(
            lambda: next(iter((runs_root / "obs").glob("*")), None),
            "the learner run dir",
        )
        wait_for(
            lambda: len(jsonl(run_dir / "metrics.jsonl")) >= 1,
            "the first epoch metrics line",
        )

        log("phase 4: SIGTERM the learner; expect the trace export ...")
        learner.send_signal(signal.SIGTERM)
        rc = learner.wait(timeout=600)
        if rc not in (0, 75):
            fail(f"learner exited rc={rc}, expected 0 or requeue 75")

        # ---- artifact assertions -------------------------------------
        final = jsonl(run_dir / "metrics.jsonl")[-1]
        for key in ("obs/scrapes_total", "obs/sources_live",
                    "obs/scrape_failed_total", "obs/slo_breaches_total"):
            if key not in final:
                fail(f"metrics.jsonl is missing the {key} column")
        if final["obs/scrape_failed_total"] != 0:
            fail("the learner's own obs columns recorded scrape failures")
        if not jsonl(run_dir / "obs.jsonl"):
            fail("obs.jsonl is empty")

        events = jsonl(run_dir / "telemetry.jsonl")
        breaches = [e for e in events if e.get("type") == "slo_breach"]
        recoveries = [
            e for e in events if e.get("type") == "slo_recovered"
        ]
        if len(breaches) != 1 or len(recoveries) != 1:
            fail(f"telemetry.jsonl: expected exactly one slo_breach + "
                 f"one slo_recovered, got {len(breaches)}/"
                 f"{len(recoveries)}")
        if breaches[0]["rule"] != "serve_goodput":
            fail(f"unexpected breach rule: {breaches[0]}")
        if breaches[0]["time"] >= recoveries[0]["time"]:
            fail("breach did not precede recovery")

        # The stitched timeline: one staging span id across >= 3 pids.
        if not trace_path.exists():
            fail("the learner exported no trace")
        trace = json.loads(trace_path.read_text())["traceEvents"]
        spans = [e for e in trace if e.get("ph") == "B"]
        pushes = {
            e["args"]["span_id"]: e["pid"] for e in spans
            if e.get("name") == "stage_push" and e["pid"] >= 100
        }
        ingests = {
            e["args"]["span_id"]: e["pid"] for e in spans
            if e.get("name") == "stage_ingest" and e["pid"] == 5
        }
        drained = {
            sid: e["pid"] for e in spans
            if e.get("name") == "drain_window"
            for sid in e.get("args", {}).get("span_ids", ())
        }
        stitched = set(pushes) & set(ingests) & set(drained)
        if not stitched:
            fail(f"no span id crosses all three lanes "
                 f"(pushes={len(pushes)} ingests={len(ingests)} "
                 f"drained={len(drained)})")
        sid = sorted(stitched)[0]
        lanes = {pushes[sid], ingests[sid], drained[sid]}
        if len(lanes) < 3:
            fail(f"span {sid} spans only pids {lanes}")
        actor_pids = {p for p in pushes.values()}
        log(f"trace stitched: span {sid} crosses pids "
            f"{sorted(lanes)} ({len(stitched)} stitched ids, actor "
            f"lanes {sorted(actor_pids)})")
    finally:
        flood_stop.set()
        for proc in (learner, fleet):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in (learner, fleet):
            if proc is not None:
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()

    log("ALL OK: three planes aggregated with zero scrape failures; "
        "the SLO hysteresis cycle emitted exactly one breach + one "
        "recovery; the exported timeline stitches one staging span id "
        "across actor, transport, and learner lanes")


if __name__ == "__main__":
    main()
