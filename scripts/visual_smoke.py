"""CPU smoke for the mixed-precision + fused-pixel-pipeline path.

``make visual-smoke`` — the full pipeline, through the REAL CLI
(docs/SCALING.md "Mixed precision & the pixel pipeline"):

1. Fused-kernel parity: the Pallas pixel kernel (interpret mode)
   agrees bitwise with its jnp reference across dtype/augment combos.
2. f32 fallback is bitwise: an on-device pixel run with
   ``--precision f32 --pixel-pipeline fused`` reproduces the default
   (reference-pipeline) run's loss/reward stream exactly, same seed —
   the fused gather moves the decode, never the numbers.
3. bf16 fused visual training runs finite end-to-end
   (``--precision bf16 --pixel-pipeline fused --frame-augment shift``)
   with telemetry on.
4. ``cost/epoch_mfu`` is present and finite in the bf16 run's
   metrics.jsonl, and its `cost` telemetry events carry the compute
   dtype — the visual-MFU regression detector is armed.

Exit 0 on success, 1 with a message on any failure.
"""

import json
import os
import pathlib
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# CPU has no table entry for roofline peaks; pin the denominators so
# cost/epoch_mfu exists and is deterministic (the cost-smoke pattern).
os.environ.setdefault("TAC_PEAK_FLOPS", "1e12")
os.environ.setdefault("TAC_PEAK_BW", "1e11")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

FAILURES = []


def check(ok, msg):
    print(("ok  " if ok else "FAIL") + " " + msg)
    if not ok:
        FAILURES.append(msg)


def kernel_parity():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torch_actor_critic_tpu.ops.augment import shift_offsets
    from torch_actor_critic_tpu.ops.pixels import fused_frame_gather

    import functools

    ring = jax.random.randint(
        jax.random.key(0), (32, 12, 20, 3), 0, 256, jnp.uint8
    )
    idx = jnp.array([0, 31, 7, 7], jnp.int32)

    # One jitted wrapper, bound once; the per-combo knobs are static
    # args (comparison runs under jit because that is where production
    # sampling runs — see tests/test_pixels.py on the /255 rewrite).
    @functools.partial(
        jax.jit, static_argnames=("out_dtype", "impl", "interpret")
    )
    def gather(r, i, offsets, out_dtype, impl, interpret=False):
        return fused_frame_gather(
            r, i, offsets=offsets, pad=4, normalize=True,
            out_dtype=out_dtype, frame_stack=2, impl=impl,
            interpret=interpret,
        )

    for out_dtype in (jnp.float32, jnp.bfloat16):
        for augment in (False, True):
            offs = (
                shift_offsets(jax.random.key(1), 4, 4) if augment else None
            )
            ref = gather(ring, idx, offs, out_dtype, "xla")
            pal = gather(ring, idx, offs, out_dtype, "pallas",
                         interpret=True)
            same = np.array_equal(
                np.asarray(ref, np.float32), np.asarray(pal, np.float32)
            )
            check(
                same,
                f"kernel parity {jnp.dtype(out_dtype).name} "
                f"augment={augment}: interpret == reference bitwise",
            )


def run_train(root, run_name, extra):
    from torch_actor_critic_tpu import train

    argv = [
        "--environment", "PixelPendulum-v0",
        "--on-device", "true",
        "--runs-root", str(root),
        "--experiment", run_name,
        "--seed", "7",
        "--epochs", "2",
        "--steps-per-epoch", "100",
        "--update-every", "50",
        "--start-steps", "50",
        "--on-device-envs", "4",
        "--buffer-size", "2000",
        "--batch-size", "16",
        "--hidden-sizes", "32,32",
        "--filters", "16,32",
        "--kernel-sizes", "4,3",
        "--strides", "2,2",
        "--cnn-dense-size", "64",
        "--cnn-features", "16",
        "--normalize-pixels", "true",
        "--no-preemption-guard",
    ] + extra
    train.main(argv)
    # One run dir per experiment root in this smoke.
    runs = sorted((root / run_name).glob("*/metrics.jsonl"))
    assert runs, f"no metrics.jsonl under {root / run_name}"
    rows = [
        json.loads(line)
        for line in runs[-1].read_text().splitlines() if line.strip()
    ]
    tele = runs[-1].parent / "telemetry.jsonl"
    events = (
        [json.loads(x) for x in tele.read_text().splitlines() if x.strip()]
        if tele.exists() else []
    )
    return rows, events


def loss_stream(rows):
    return [
        (r.get("loss_q"), r.get("loss_pi"), r.get("reward"), r.get("episodes"))
        for r in rows
    ]


def main():
    kernel_parity()

    import numpy as np

    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        # 2. Bitwise f32 fallback: default (reference pipeline) vs the
        # fused pipeline at --precision f32, same seed.
        ref_rows, _ = run_train(root, "ref_f32", [])
        fus_rows, _ = run_train(
            root, "fused_f32",
            ["--precision", "f32", "--pixel-pipeline", "fused"],
        )
        check(
            loss_stream(ref_rows) == loss_stream(fus_rows),
            "f32 fused pipeline bitwise-matches the reference pipeline "
            "loss/reward stream through the real CLI",
        )

        # 3./4. bf16 + fused + DrQ shift, telemetry on -> finite losses
        # and the cost/mfu regression detector present.
        bf_rows, bf_events = run_train(
            root, "fused_bf16",
            [
                "--precision", "bf16", "--pixel-pipeline", "fused",
                "--frame-augment", "shift", "--telemetry", "true",
            ],
        )
        finite = all(
            np.isfinite(r["loss_q"]) and np.isfinite(r["loss_pi"])
            for r in bf_rows
        )
        check(finite and len(bf_rows) == 2,
              "bf16 fused visual training finite over 2 epochs")
        mfu = [r.get("cost/epoch_mfu") for r in bf_rows if "cost/epoch_mfu" in r]
        check(
            bool(mfu) and all(np.isfinite(v) and v > 0 for v in mfu),
            "cost/epoch_mfu present and finite in metrics.jsonl",
        )
        cost_events = [e for e in bf_events if e.get("type") == "cost"]
        check(
            bool(cost_events)
            and all(
                e.get("compute_dtype") == "bfloat16" for e in cost_events
            ),
            "cost telemetry events carry the compute dtype",
        )

    if FAILURES:
        print(f"\nvisual-smoke: {len(FAILURES)} failure(s)")
        return 1
    print("\nvisual-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
