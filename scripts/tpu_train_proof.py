"""Real-chip training proof: converge on-device, evaluate on the host.

Trains SAC on the pure-JAX Pendulum twin with the fused on-device loop
(one dispatch per epoch, ``sac/ondevice.py``) at the reference model
configuration (batch 64, hidden [256,256], update_every 50 — ref
``main.py:147-160``) through the REAL product CLI (``train.py``), then
evaluates the resulting checkpoint on the host gymnasium ``Pendulum-v1``
through the real eval CLI (``run_agent.py``). This closes the loop the
throughput bench cannot: a policy trained *entirely on the chip*
controls the real host environment.

Two proof families, selected by ``--task``:

- ``pendulum`` (default, 5 epochs): flat SAC on the exact-dynamics
  Pendulum twin; artifact ``runs/tpu/train_proof_<utc>.json``. Solved
  = eval > -350 (host parity band: torch -120.3, our host loop
  -119.4).
- ``pixel`` (30 epochs): visual SAC with the shared DrQ recipe
  (``sac/ondevice.PIXEL_RECIPE``) on the on-chip-rendered
  ``PixelPendulumBalance`` twin; artifact
  ``runs/tpu/train_proof_pixel_<utc>.json``. Solved = eval > -400
  (measured random policy -873.7; the CPU-budget curves in
  ``runs/pixelbal-*`` plateau ~-770 — this is the pixel-learning
  demonstration only the chip's throughput can reach).

Artifacts write incrementally (training result first, eval appended),
so a tunnel death mid-proof keeps the training half. Run by
``scripts/tpu_watch.sh`` while unsolved (pixel: max 3 attempts), and
manually any time:

    python scripts/tpu_train_proof.py [--task pixel] [--epochs N]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument(
        "--task", choices=["pendulum", "pixel", "cheetah"], default="pendulum",
        help="pendulum: flat SAC on the exact-dynamics Pendulum twin. "
        "pixel: visual SAC (DrQ recipe) on the on-chip-rendered "
        "PixelPendulumBalance twin — the pixel-learning proof the CPU "
        "budget cannot reach (runs/pixelbal-* curves improve ~200 "
        "return over 32k steps but stay under-trained; the chip does "
        "120k steps in minutes through the fused visual loop). "
        "cheetah: sim-to-sim transfer probe — train on the SURROGATE "
        "CheetahRunJax dynamics (envs/ondevice.py documents the "
        "deliberate non-parity; MJX/Brax absent from this image), "
        "evaluate on real host MuJoCo HalfCheetah-v5. Quantifies how "
        "much of the surrogate-learned gait survives contact with the "
        "true dynamics; an unsolved result is itself the honest "
        "measurement of the surrogate gap (VERDICT r4 #5).",
    )
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--steps-per-epoch", type=int, default=4000)
    p.add_argument("--on-device-envs", type=int, default=4)
    p.add_argument("--eval-episodes", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--allow-cpu", action="store_true",
        help="Run the proof pipeline on the CPU backend (self-test; the "
        "artifact records the backend, so it cannot masquerade as chip "
        "evidence)",
    )
    args = p.parse_args(argv)

    info, _ = bench.preflight_backend()
    if info.get("platform") in (None, "none", "cpu") and not args.allow_cpu:
        print(f"no accelerator backend ({info}); nothing to prove")
        return 1
    if info.get("platform") in (None, "none"):
        info = {"platform": "cpu", "device_kind": "cpu"}

    pixel = args.task == "pixel"
    cheetah = args.task == "cheetah"
    if args.epochs is None:
        args.epochs = 30 if pixel else (25 if cheetah else 5)
    env_name = (
        "PixelPendulumBalance-v0" if pixel
        else "HalfCheetah-v5" if cheetah
        else "Pendulum-v1"
    )
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    runs_root = "runs/train_proof"  # gitignored; only the JSON artifact is committed
    # A CPU self-test must not land in the committed chip-evidence tree
    # (it would also satisfy the watch loop's one-shot guard) — mirror
    # bench.persist_tpu_artifact's cpu refusal.
    if info.get("platform") == "cpu":
        evidence_dir = runs_root
    else:
        evidence_dir = bench.TPU_EVIDENCE_DIR
    os.makedirs(evidence_dir, exist_ok=True)
    prefix = (
        "train_proof_pixel" if pixel
        else "train_proof_cheetah" if cheetah
        else "train_proof"
    )
    path = os.path.join(evidence_dir, f"{prefix}_{stamp}.json")
    # Single source for the run configuration: the CLI args, the
    # artifact's config block, and the warmup accounting all derive
    # from this dict (reference model config, ref main.py:147-160).
    train_cfg = {
        "epochs": args.epochs,
        "steps_per_epoch": args.steps_per_epoch,
        "on_device_envs": args.on_device_envs,
        "batch_size": 64,
        "hidden_sizes": "256,256",
        "update_every": 50,
        "start_steps": 1000,
        "buffer_size": 100000,
        "seed": args.seed,
    }
    if pixel:
        # The ONE shared pixel recipe (sac/ondevice.PIXEL_RECIPE —
        # same config the committed pixelbal-* evidence runs and the
        # bench's pixel row use); tuples rendered as CLI csv.
        from torch_actor_critic_tpu.sac.ondevice import PIXEL_RECIPE

        train_cfg.update({
            k: ",".join(map(str, v)) if isinstance(v, tuple) else v
            for k, v in PIXEL_RECIPE.items()
        })
    out = {
        "proof": "on-device training -> host-env eval (scripts/tpu_train_proof.py)",
        "backend": info.get("platform"),
        "device_kind": info.get("device_kind"),
        "captured_utc": stamp,
        "env": (
            f"{env_name} (pure-JAX twin on chip — pixel frames "
            "rasterized on device; host env on eval)" if pixel else
            "HalfCheetah-v5 (SURROGATE CheetahRunJax dynamics on chip "
            "— deliberate non-parity, envs/ondevice.py; real MuJoCo on "
            "host eval: this artifact MEASURES the sim-to-sim transfer "
            "gap)" if cheetah else
            "Pendulum-v1 (pure-JAX twin on chip; gymnasium on host eval)"
        ),
        "config": dict(train_cfg),
    }

    def flush():
        with open(path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)

    flush()

    from torch_actor_critic_tpu.run_agent import main as eval_main
    from torch_actor_critic_tpu.train import main as train_main

    # Per-task experiment dir: concurrent proofs of DIFFERENT tasks
    # (e.g. the watch loop's pendulum/pixel chip proofs landing while a
    # long CPU cheetah probe trains) must not trip each other's
    # exactly-one-new-run guard below.
    experiment = f"proof-{args.task}"
    exp_dir = pathlib.Path(runs_root, experiment)
    runs_before = (
        {d.name for d in exp_dir.iterdir()} if exp_dir.exists() else set()
    )

    t0 = time.time()
    metrics = train_main([
        "--environment", env_name,
        "--on-device", "true",
        "--devices", "1",
        "--runs-root", runs_root,
        "--experiment", experiment,
    ] + [
        f"--{k.replace('_', '-')}={v}" for k, v in train_cfg.items()
    ])
    train_s = time.time() - t0
    grad_steps = train_cfg["epochs"] * train_cfg["steps_per_epoch"]
    # Policy-free warmup phase, stepped by every env (the trainer's own
    # formula — no drift).
    from torch_actor_critic_tpu.sac.ondevice import warmup_steps

    warmup_env_steps = warmup_steps(
        train_cfg["start_steps"], train_cfg["update_every"]
    ) * train_cfg["on_device_envs"]
    out["train"] = {
        "wall_s": round(train_s, 1),
        "grad_steps": grad_steps,
        "env_steps": grad_steps * train_cfg["on_device_envs"] + warmup_env_steps,
        "warmup_env_steps": warmup_env_steps,
        "grad_steps_per_sec_incl_compile_and_warmup": round(grad_steps / train_s, 1),
        "final_epoch_metrics": {k: round(float(v), 3) for k, v in metrics.items()},
    }
    flush()
    print(f"[proof] trained {grad_steps} grad steps in {train_s:.1f}s -> {path}")

    new_runs = {d.name for d in exp_dir.iterdir()} - runs_before
    if len(new_runs) != 1:
        raise RuntimeError(
            f"expected exactly one new run under {exp_dir}, found {sorted(new_runs)} "
            "(concurrent invocation?)"
        )
    run_id = new_runs.pop()
    eval_metrics = eval_main([
        "--run", run_id,
        "--runs-root", runs_root,
        "--experiment", experiment,
        "--episodes", str(args.eval_episodes),
        "--headless",
        "--seed", str(args.seed),
    ])
    # Thresholds: flat Pendulum — host parity band (torch -120.3, ours
    # -119.4), -350 leaves seed headroom. Pixel balance — the measured
    # random policy is -873.7 and the CPU-budget runs plateau ~-770
    # (PARITY.md "Pixel learning"); -400 means the chip-trained pixel
    # policy holds the pendulum up most of the episode. Cheetah
    # transfer — a random policy scores ~-300 on HalfCheetah-v5 and a
    # real 100k-step MuJoCo-trained SAC ~2300 (runs/bf16cheetah); 500
    # means a meaningful fraction of the surrogate gait survives the
    # true contact dynamics. solved=false is still the measurement.
    threshold = -400.0 if pixel else (500.0 if cheetah else -350.0)
    out["eval"] = {
        "episodes": args.eval_episodes,
        "ep_ret_mean": round(float(eval_metrics["ep_ret_mean"]), 1),
        "ep_ret_std": round(float(eval_metrics["ep_ret_std"]), 1),
        "host_env": env_name,
        "solved_band_threshold": threshold,
        "solved": float(eval_metrics["ep_ret_mean"]) > threshold,
    }
    if pixel:
        out["eval"]["random_policy_baseline"] = -873.7
    if cheetah:
        out["eval"]["context"] = {
            "random_policy_approx": -300.0,
            "mujoco_trained_100k": 2344.4,  # runs/bf16cheetah
            "note": "policy trained on surrogate dynamics; this eval "
            "measures the transfer gap, not framework learning "
            "capacity (that is the host-loop 1M-step TD3 gate)",
        }
    flush()
    print(f"[proof] eval on host env: {out['eval']['ep_ret_mean']} "
          f"(solved={out['eval']['solved']}) -> {path}")
    return 0 if out["eval"]["solved"] else 2


if __name__ == "__main__":
    sys.exit(main())
