#!/usr/bin/env python
"""Decoupled-plane chaos smoke (``make decouple-smoke``).

Proves the full "training cluster feeds serving fleet" story survives
both role deaths in ONE run (docs/RESILIENCE.md "Decoupled-plane
failure modes"):

Phase 1 — in-process bitwise proof: SIGTERM (programmatic, step-exact)
lands mid-epoch on a decoupled learner whose staging buffer holds an
undrained tail; the resumed run's final learner state AND replay ring
are **bitwise identical** to an uninterrupted twin — zero accepted
transitions lost.

Phase 2 — subprocess chaos, real signals, real HTTP:

1. a decoupled learner (``train.py --decoupled true --serve-url ...``)
   starts against a serving port where NOTHING listens yet: actors
   degrade to the local snapshot from step one (counted);
2. a real serving worker (``serve.py --run <id>``) comes up on that
   port, hot-reload-polling the learner's checkpoints: actors probe,
   RE-HOME, and act through HTTP;
3. the serving worker is **SIGKILLed mid-collection**: actors degrade
   again — envs never stall, the learner keeps training;
4. the learner gets **SIGTERM mid-epoch**: it checkpoints staging +
   replay and exits with requeue code 75;
5. the learner resumes (``--run <id>``) and completes.

Phase 3 — actor-process fleet chaos (``train.py --actors 3``), real
subprocess actors over the networked staging transport, pushes made
flaky via TAC_FLAKY_PUSH:

1. the learner comes up with 3 supervised actor subprocesses feeding
   its staging buffer over HTTP (flaky push path: drops + latency);
2. one actor is **SIGKILLed mid-collection**: the supervisor declares
   it dead, purges its staged tail (counted ``dropped_dead_actor``),
   and restarts the slot as a new incarnation (counted
   ``actor_restarts``);
3. the learner gets **SIGTERM mid-epoch**: drains, checkpoints the
   staged tail + per-actor dedup watermarks, exits 75;
4. the learner resumes (``--run <id>``) on the SAME transport port,
   respawns the fleet above the restored watermarks, completes rc 0.

Asserted at the end: requeue/rc discipline, zero accepted transitions
lost (the staging conservation invariant — including the dead-actor
term — over the WHOLE run, across the restart), at least one
supervised restart, every accepted push accounted per actor (the
sequence-number audit), every recorded generation lag <=
--max-actor-lag, at least one degradation AND one re-home observed,
and finite final metrics.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

MAX_ACTOR_LAG = 4

TRAIN_FLAGS = [
    "--environment", "Pendulum-v1",
    "--hidden-sizes", "16,16",
    "--batch-size", "16",
    # Per invocation: 2 epochs of 200 steps (resume adds 2 more each
    # time). Long enough that signals sent right after an epoch line
    # appears land MID-epoch, short enough for a CI smoke.
    "--epochs", "2",
    "--steps-per-epoch", "200",
    "--start-steps", "20",
    "--update-after", "20",
    "--update-every", "20",
    "--buffer-size", "2000",
    "--max-ep-len", "200",
    "--save-every", "1",
    "--decoupled", "true",
    "--max-actor-lag", str(MAX_ACTOR_LAG),
    "--actor-timeout-s", "2.0",
    "--telemetry", "true",
]


def log(msg):
    print(f"[decouple-smoke] {msg}", flush=True)


def fail(msg):
    log(f"FAIL: {msg}")
    sys.exit(1)


# --------------------------------------------------- phase 1: bitwise


def phase_bitwise(root: Path):
    import numpy as np

    from tests.test_decoupled import (  # reuse the pinned helpers
        comparable_state,
        make_trainer,
    )
    from torch_actor_critic_tpu.resilience import (
        Preempted,
        PreemptionGuard,
    )
    from torch_actor_critic_tpu.resilience.faultinject import FaultyEnvPool

    # steps_per_epoch=44: the epoch-1 boundary (step 88) sits 8 steps
    # past the last window drain (step 80), so the preemption save
    # carries a staged-but-undrained tail that must round-trip.
    over = dict(epochs=3, steps_per_epoch=44, save_every=10)
    log("phase 1: uninterrupted twin ...")
    tra = make_trainer(root / "a", **over)
    try:
        tra.train()
        ref = comparable_state(tra)
    finally:
        tra.close()

    log("phase 1: preempted run (SIGTERM at lockstep step 50) ...")
    guard = PreemptionGuard()  # programmatic: exact, signal-free
    trb = make_trainer(root / "b", preemption=guard, **over)
    trb.pool = FaultyEnvPool(trb.pool).call_at(
        50, lambda: guard.request_preemption()
    )
    preempted = False
    try:
        try:
            trb.train()
        except Preempted:
            preempted = True
    finally:
        trb.close()
    if not preempted:
        fail("phase 1: the preemption never fired")
    staged_tail = trb.checkpointer.peek_meta()["decoupled"]["staging"][
        "count"
    ]
    if staged_tail != 8:
        fail(f"phase 1: expected an 8-transition staged tail, got "
             f"{staged_tail}")

    log("phase 1: resume and compare ...")
    trc = make_trainer(root / "b", **{**over, "epochs": 1})
    try:
        if trc.restore() != 2:
            fail("phase 1: resume landed on the wrong epoch")
        if trc.staging.depth() != 8:
            fail("phase 1: staged tail lost across the restart")
        trc.train()
        got = comparable_state(trc)
        if not trc.staging.conservation_holds():
            fail("phase 1: staging conservation violated")
    finally:
        trc.close()
    for x, y in zip(ref, got):
        np.testing.assert_array_equal(x, y)
    log("phase 1 OK: bitwise resume incl. the staged tail "
        f"({staged_tail} transitions)")


# ---------------------------------------------------- phase 2: chaos


def metrics_lines(path: Path):
    if not path.exists():
        return []
    out = []
    for line in path.read_text().splitlines():
        try:
            out.append(json.loads(line))
        except ValueError:
            pass
    return out


def wait_for(predicate, what, timeout_s=240.0, poll_s=0.25):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(poll_s)
    fail(f"timed out after {timeout_s:.0f}s waiting for {what}")


def free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def phase_chaos(root: Path):
    """Real processes, real signals. Epoch-gated choreography (every
    signal fires right after an epoch's metrics line lands, i.e. just
    as the next epoch's collection starts — nothing is timed against
    wall-clock guesses):

    run 1   learner alone, serving DOWN: actors degrade from the first
            policy step; exits 0 leaving checkpoints.
    worker  serve.py --run comes up on the port, hot-reload-polling.
    run 2   learner resumes: actors act THROUGH the worker over HTTP;
            after its first epoch line, the worker is SIGKILLed and the
            learner SIGTERMed — both land mid-collection of the next
            epoch; the learner checkpoints and exits 75.
    run 3   learner resumes degraded and completes, rc 0.
    """
    import urllib.request as urlreq

    runs_root = root / "runs"
    port = free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def launch_learner(extra):
        return subprocess.Popen(
            [sys.executable, "-m", "torch_actor_critic_tpu.train",
             *extra,
             "--runs-root", str(runs_root), "--experiment", "decouple"],
            cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

    log(f"phase 2: run 1 — learner alone, serving :{port} DOWN "
        "(actors must degrade, envs must not stall) ...")
    learner = launch_learner(
        TRAIN_FLAGS + ["--serve-url", f"http://127.0.0.1:{port}"]
    )
    worker = None
    try:
        rc = learner.wait(timeout=600)
        if rc != 0:
            fail(f"run 1 exited rc={rc}")
        run_dir = next(iter((runs_root / "decouple").glob("*")), None)
        if run_dir is None:
            fail("run 1 left no run dir")
        run_id = run_dir.name
        metrics = run_dir / "metrics.jsonl"
        lines = metrics_lines(metrics)
        if not lines:
            fail("run 1 logged no epochs")
        if lines[-1].get("decoupled/fallback_actions_total", 0) <= 0:
            fail("expected fallback actions while serving was down")
        if lines[-1].get("decoupled/degradations_total", 0) < 1:
            fail("expected a degradation while serving was down")

        log(f"phase 2: starting serving worker for run {run_id} ...")
        worker = subprocess.Popen(
            [sys.executable, str(REPO / "serve.py"),
             "--run", run_id, "--runs-root", str(runs_root),
             "--experiment", "decouple", "--port", str(port),
             "--poll-interval", "0.25", "--max-batch", "4"],
            cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

        def healthy():
            try:
                with urlreq.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2
                ) as r:
                    return r.status == 200
            except Exception:
                return False

        wait_for(healthy, "the serving worker's /healthz")

        log("phase 2: run 2 — resume; actors act over HTTP ...")
        n_before = len(metrics_lines(metrics))
        served_before = metrics_lines(metrics)[-1].get(
            "decoupled/serving_actions_total", 0
        )
        learner = launch_learner(["--run", run_id])
        first_line = wait_for(
            lambda: (
                metrics_lines(metrics)[n_before]
                if len(metrics_lines(metrics)) > n_before else None
            ),
            "run 2's first epoch line",
        )
        if first_line.get(
            "decoupled/serving_actions_total", 0
        ) <= served_before:
            fail("run 2's actors never acted through the serving worker")
        log("phase 2: SIGKILL the serving worker + SIGTERM the learner "
            "mid-collection of the next epoch ...")
        worker.send_signal(signal.SIGKILL)
        worker.wait(timeout=30)
        learner.send_signal(signal.SIGTERM)
        rc = learner.wait(timeout=600)
        if rc != 75:
            fail(f"run 2 exited rc={rc}, expected the requeue code 75")
        log("phase 2: learner exited 75 (requeue); run 3 — resume "
            "degraded to completion ...")

        learner = launch_learner(["--run", run_id])
        rc = learner.wait(timeout=600)
        if rc != 0:
            fail(f"run 3 exited rc={rc}")

        final = metrics_lines(metrics)[-1]
        for key in ("loss_q", "loss_pi", "reward"):
            if not _finite(final.get(key)):
                fail(f"final {key} not finite: {final.get(key)}")
        # Conservation over the WHOLE run, across BOTH restarts: every
        # accepted transition was drained, dropped-by-policy, or is
        # still staged (depth) — none silently lost.
        staged = final["decoupled/staged_total"]
        accounted = (
            final["decoupled/drained_total"]
            + final["decoupled/dropped_stale_total"]
            + final["decoupled/dropped_backpressure_total"]
            + final["decoupled/staging_depth"]
        )
        if staged != accounted:
            fail(f"staging conservation violated: staged={staged} vs "
                 f"accounted={accounted}")
        if final["decoupled/actor_lag_max"] > MAX_ACTOR_LAG:
            fail(f"recorded lag {final['decoupled/actor_lag_max']} "
                 f"exceeds --max-actor-lag {MAX_ACTOR_LAG}")
        if final["decoupled/degradations_total"] < 2:
            fail("expected >= 2 degradations (cold start + worker kill)")
        if final["decoupled/serving_actions_total"] <= 0:
            fail("expected serving-plane actions while the worker lived")
        log(
            "phase 2 OK: staged=%d drained=%d dropped_stale=%d "
            "depth=%d lag_max=%s served=%d fallbacks=%d "
            "degradations=%d" % (
                staged, final["decoupled/drained_total"],
                final["decoupled/dropped_stale_total"],
                final["decoupled/staging_depth"],
                final["decoupled/actor_lag_max"],
                final["decoupled/serving_actions_total"],
                final["decoupled/fallback_actions_total"],
                final["decoupled/degradations_total"],
            )
        )
    finally:
        for proc in (learner, worker):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)


def _finite(v):
    try:
        return v is not None and abs(float(v)) < float("inf")
    except (TypeError, ValueError):
        return False


# ------------------------------------------------ phase 3: actor fleet


def phase_fleet(root: Path):
    """SIGKILL an actor subprocess, SIGTERM the learner, resume — all
    over the networked staging transport with a flaky push path."""
    import urllib.request as urlreq

    runs_root = root / "runs"
    fleet_port = free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # Transport flap on every actor's push path: 20% drops plus
        # 5ms latency, under the client's retry/backoff loop.
        TAC_FLAKY_PUSH="drop_rate=0.2,latency_s=0.005,seed=1",
    )
    flags = TRAIN_FLAGS + [
        "--actors", "3",
        "--actor-max-restarts", "3",
        # Loose deadline: 3 actor processes + the learner share one CI
        # CPU, and a jax-compile stall is scheduling pressure, not
        # death — the injected SIGKILL is what must drive the restart.
        "--heartbeat-timeout-s", "10",
        # Pinned so the resumed learner rebinds the same address and
        # /metrics stays reachable across the restart.
        "--fleet-port", str(fleet_port),
        "--epochs", "3",
    ]

    def launch(extra):
        return subprocess.Popen(
            [sys.executable, "-m", "torch_actor_critic_tpu.train",
             *flags, *extra,
             "--runs-root", str(runs_root), "--experiment", "fleet"],
            cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

    def transport_metrics():
        try:
            with urlreq.urlopen(
                f"http://127.0.0.1:{fleet_port}/metrics", timeout=2
            ) as r:
                return json.loads(r.read().decode())
        except Exception:
            return None

    log(f"phase 3: learner + 3 supervised actor subprocesses "
        f"(transport :{fleet_port}, flaky pushes) ...")
    learner = launch([])
    try:
        run_dir = wait_for(
            lambda: next(iter((runs_root / "fleet").glob("*")), None),
            "the fleet run dir",
        )
        run_id = run_dir.name
        metrics = run_dir / "metrics.jsonl"

        # Wait until the fleet actually feeds the learner over HTTP.
        snap = wait_for(
            lambda: (
                (m := transport_metrics()) is not None
                and m["transport"]["accepted_total"] > 0
                and len(m["transport"]["actors"]) >= 3
                and m
            ),
            "fleet pushes over the transport",
        )
        victim_pid = next(
            a["pid"] for a in snap["transport"]["actors"].values()
            if a.get("pid") not in (None, learner.pid)
        )
        log(f"phase 3: SIGKILL actor pid {victim_pid} mid-collection ...")
        os.kill(victim_pid, signal.SIGKILL)

        restarts = wait_for(
            lambda: (
                (m := transport_metrics()) is not None
                and len(m["transport"]["actors"]) >= 3
                and metrics_lines(metrics)
                and metrics_lines(metrics)[-1].get(
                    "decoupled/actor_restarts", 0
                ) >= 1
                and metrics_lines(metrics)[-1]
            ),
            "the supervised restart to reach the metrics log",
        )
        log("phase 3: restart observed (actor_restarts="
            f"{restarts['decoupled/actor_restarts']}); SIGTERM the "
            "learner mid-epoch ...")
        learner.send_signal(signal.SIGTERM)
        rc = learner.wait(timeout=600)
        if rc != 75:
            fail(f"fleet learner exited rc={rc}, expected requeue 75")

        log("phase 3: resume with reconnecting fleet ...")
        learner = launch(["--run", run_id])
        rc = learner.wait(timeout=600)
        if rc != 0:
            fail(f"fleet resume exited rc={rc}")

        final = metrics_lines(metrics)[-1]
        for key in ("loss_q", "loss_pi", "reward"):
            if not _finite(final.get(key)):
                fail(f"final {key} not finite: {final.get(key)}")
        # The EXTENDED conservation invariant, across the actor kill
        # AND the learner restart: every staged transition drained,
        # dropped by an accounted policy, purged with its dead actor,
        # or still in the buffer.
        staged = final["decoupled/staged_total"]
        accounted = (
            final["decoupled/drained_total"]
            + final["decoupled/dropped_stale_total"]
            + final["decoupled/dropped_backpressure_total"]
            + final["decoupled/dropped_dead_actor_total"]
            + final["decoupled/staging_depth"]
        )
        if staged != accounted:
            fail(f"fleet conservation violated: staged={staged} vs "
                 f"accounted={accounted}")
        if final.get("decoupled/conservation_ok") != 1:
            fail("the learner's own epoch-boundary conservation check "
                 "went red")
        if final["decoupled/actor_restarts"] < 1:
            fail("expected >= 1 supervised actor restart")
        if final["decoupled/transport_accepted_total"] <= 0:
            fail("the fleet never fed the learner over the transport")
        if final["decoupled/transport_rejected_malformed_total"] != 0:
            fail("well-formed fleet pushes were rejected as malformed")
        if final["decoupled/actor_lag_max"] > MAX_ACTOR_LAG:
            fail(f"recorded lag {final['decoupled/actor_lag_max']} "
                 f"exceeds --max-actor-lag {MAX_ACTOR_LAG}")
        log(
            "phase 3 OK: staged=%d drained=%d dead_actor=%d depth=%d "
            "accepted=%d duplicates=%d restarts=%d" % (
                staged, final["decoupled/drained_total"],
                final["decoupled/dropped_dead_actor_total"],
                final["decoupled/staging_depth"],
                final["decoupled/transport_accepted_total"],
                final["decoupled/transport_duplicate_pushes_total"],
                final["decoupled/actor_restarts"],
            )
        )
    finally:
        if learner.poll() is None:
            learner.kill()
            learner.wait(timeout=30)


def main():
    import tempfile

    with tempfile.TemporaryDirectory(prefix="decouple_smoke_") as td:
        root = Path(td)
        phase_bitwise(root / "bitwise")
        phase_chaos(root / "chaos")
        phase_fleet(root / "fleet")
    log("ALL OK: both role kills survived; zero accepted transitions "
        "lost; replay bitwise across the learner resume; staleness "
        "bounded by the lag knob; the actor fleet survived a SIGKILL + "
        "learner restart with the extended invariant green")


if __name__ == "__main__":
    main()
