"""End-to-end smoke of the learning-health diagnostics stack.

Runs a tiny full-tier CPU training job through the real CLI entry point
(``--diagnostics full --telemetry true``) and asserts the contract
docs/OBSERVABILITY.md "Learning-health diagnostics" promises:

- every post-warmup ``metrics.jsonl`` row carries the full diagnostic
  key set, with finite (non-null) values;
- ``telemetry.jsonl`` holds one strict-JSON ``diagnostics`` event per
  update epoch whose TD-histogram snapshot is internally consistent
  (count > 0, p50 <= p95 <= p99 <= max);
- epoch events carry the recompilation watchdog's ``xla_compiles``
  count, which is positive and non-decreasing.

The ``make diag-smoke`` gate; ~60s on a 2-thread CPU host.
"""

import json
import math
import os
import sys
import tempfile
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The metric keys every full-tier update epoch must report
# (docs/OBSERVABILITY.md metric glossary).
DIAG_KEYS = (
    "diag/grad_norm_q",
    "diag/grad_norm_pi",
    "diag/update_ratio_q",
    "diag/update_ratio_pi",
    "diag/q_min",
    "diag/q_max",
    "diag/q_spread",
    "diag/q_bias",
    "diag/act_sat",
    "diag/param_norm",
    "diag/td_abs_min",
    "diag/td_abs_max",
    "diag/td_abs_sum",
    "loss_q_max",
    "loss_pi_max",
    "early_warnings",
    "xla_compiles",
)


def fail(msg):
    print(f"[diag-smoke] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from torch_actor_critic_tpu.train import main as train_main

    root = Path(tempfile.mkdtemp(prefix="diag_smoke_"))
    train_main([
        "--environment", "Pendulum-v1",
        "--devices", "1",
        "--runs-root", str(root),
        "--epochs", "3",
        "--steps-per-epoch", "60",
        "--start-steps", "20",
        "--update-after", "20",
        "--update-every", "10",
        "--batch-size", "16",
        "--buffer-size", "500",
        "--hidden-sizes", "16,16",
        "--max-ep-len", "100",
        "--diagnostics", "full",
        "--telemetry", "true",
    ])
    run_dir = next((root / "Default").iterdir())
    print(f"[diag-smoke] run dir: {run_dir}")

    # --- metrics.jsonl: full diagnostic key set, finite values ---
    rows = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    if not rows:
        fail("no metrics rows")
    for row in rows:
        for key in DIAG_KEYS:
            if key not in row:
                fail(f"metrics row (step {row.get('step')}) missing {key}")
            v = row[key]
            # The tracker maps non-finite to null; a null diagnostic
            # means the in-graph reduction produced NaN/inf.
            if v is None or not math.isfinite(float(v)):
                fail(f"{key} is non-finite in step {row.get('step')}: {v!r}")
        if not (row["diag/q_min"] <= row["diag/q_max"]):
            fail(f"q_min > q_max in step {row.get('step')}")
        if row["diag/td_abs_min"] > row["diag/td_abs_max"]:
            fail(f"td_abs_min > td_abs_max in step {row.get('step')}")
        if not 0.0 <= row["diag/act_sat"] <= 1.0:
            fail(f"act_sat outside [0,1]: {row['diag/act_sat']}")
    print(f"[diag-smoke] metrics ok: {len(rows)} rows x {len(DIAG_KEYS)} "
          "diagnostic keys, all finite")

    # --- telemetry.jsonl: diagnostics events + watchdog counts ---
    events = [
        json.loads(line)
        for line in (run_dir / "telemetry.jsonl").read_text().splitlines()
    ]
    diag_events = [e for e in events if e["type"] == "diagnostics"]
    if len(diag_events) != len(rows):
        fail(
            f"expected {len(rows)} diagnostics events, got {len(diag_events)}"
        )
    for ev in diag_events:
        hist = ev.get("td_hist")
        if not hist or hist.get("td_abs_count", 0) <= 0:
            fail(f"epoch {ev['epoch']}: empty TD histogram snapshot {hist}")
        p50, p95, p99, mx = (
            hist["td_abs_p50"], hist["td_abs_p95"],
            hist["td_abs_p99"], hist["td_abs_max"],
        )
        if not p50 <= p95 <= p99 <= mx:
            fail(f"epoch {ev['epoch']}: TD percentiles disordered {hist}")
        for key in ("diag/grad_norm_q", "diag/q_bias", "diag/act_sat"):
            if key not in ev["metrics"]:
                fail(f"diagnostics event missing metrics[{key!r}]")
    epochs = [e for e in events if e["type"] == "epoch"]
    compiles = [e.get("xla_compiles") for e in epochs]
    if any(c is None or c <= 0 for c in compiles):
        fail(f"epoch events missing positive xla_compiles: {compiles}")
    if compiles != sorted(compiles):
        fail(f"xla_compiles not non-decreasing: {compiles}")
    print(f"[diag-smoke] telemetry ok: {len(diag_events)} diagnostics "
          f"events, TD histogram consistent, xla_compiles {compiles}")
    print("[diag-smoke] PASS")


if __name__ == "__main__":
    main()
