"""Fleet smoke: 3 workers + router, worker kill, rolling reload.

End-to-end proof of docs/SERVING.md "Fleet" through the REAL operator
entry point (``serve.py --fleet 3`` — worker subprocesses on ephemeral
ports behind the health-gated router), on CPU, ~2 min:

1. **Flood + kill**: a closed-loop client herd (HTTP ``PolicyClient``
   with Retry-After-honoring retries) floods the router; one worker is
   SIGKILLed MID-flood. Asserts every client request is answered (the
   router fails in-flight proxies over to surviving workers; zero
   accepted-request drops), membership ejects the dead worker, and
   goodput continues after the kill.
2. **Rolling reload**: a newer checkpoint epoch appears; ``POST
   /reload`` on the router rolls it across the fleet one worker at a
   time. Asserts surviving workers reload to the new epoch and are
   re-admitted, the dead worker reports an error without aborting the
   roll, and the aggregated ``/metrics`` carries per-worker labels +
   merged latency percentiles from the survivors.
3. **Teardown**: SIGTERM to the fleet parent drains workers gracefully
   and exits 0.

Exits nonzero on any violated invariant; prints a one-line JSON
summary for CI logs.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path
from urllib import request as urlreq

REPO = str(Path(__file__).resolve().parent.parent)
sys.path.insert(0, REPO)
OBS_DIM, ACT_DIM = 17, 6


def fail(msg, proc=None):
    print(f"[fleet-smoke] FAIL: {msg}", file=sys.stderr)
    if proc is not None:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=10)
            if out:
                print(out[-3000:], file=sys.stderr)
        except subprocess.TimeoutExpired:
            proc.kill()
    sys.exit(1)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.serve import PolicyClient
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    tmp = tempfile.mkdtemp(prefix="fleet_smoke_")
    ckpt_dir = os.path.join(tmp, "ckpts")
    cfg = SACConfig(hidden_sizes=(32, 32))
    sac = SAC(
        cfg,
        Actor(act_dim=ACT_DIM, hidden_sizes=(32, 32)),
        DoubleCritic(hidden_sizes=(32, 32)),
        ACT_DIM,
    )

    def save_epoch(epoch, seed):
        ck = Checkpointer(ckpt_dir, save_buffer=False)
        try:
            ck.save(
                epoch,
                sac.init_state(jax.random.key(seed), jnp.zeros((OBS_DIM,))),
                extra={"config": cfg.to_json()}, wait=True,
            )
        finally:
            ck.close()

    save_epoch(0, seed=0)
    print(f"[fleet-smoke] checkpoint written: {ckpt_dir}")

    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + (
            os.pathsep + os.environ["PYTHONPATH"]
            if os.environ.get("PYTHONPATH") else ""
        ),
        PALLAS_AXON_POOL_IPS="",
    )
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "serve.py"),
            "--fleet", "3", "--port", "0",
            "--ckpt-dir", ckpt_dir,
            "--obs-dim", str(OBS_DIM), "--act-dim", str(ACT_DIM),
            "--max-batch", "8", "--max-wait-ms", "1",
            "--poll-interval", "0",  # reload only via the rolling roll
            "--router-poll", "0.5",
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )

    info, deadline = None, time.time() + 300
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                fail(f"fleet exited rc={proc.returncode} before ready", proc)
            time.sleep(0.1)
            continue
        sys.stderr.write("[fleet] " + line)
        if line.startswith("{") and '"router"' in line:
            try:
                info = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    if info is None:
        fail("fleet never printed its router address", proc)
    router = info["router"]
    pids = info["pids"]
    assert len(pids) == 3, info
    print(f"[fleet-smoke] fleet up: router {router}, worker pids {pids}")
    threading.Thread(
        target=lambda: [None for _ in proc.stdout], daemon=True
    ).start()  # keep the parent's stdout pipe drained

    summary = {}
    try:
        obs = np.linspace(-1, 1, OBS_DIM).astype(np.float32)

        # ------------------------------------------- 1. flood + kill
        n_threads, per_thread = 6, 40
        kill_after = 60  # responses before the kill
        answered, errors = [0], []
        count_lock = threading.Lock()
        killed = threading.Event()
        t_kill_response_mark = [0]

        def flooder(i):
            client = PolicyClient(url=router, retries=3, backoff_s=0.1)
            local_obs = obs + 0.01 * i
            for _ in range(per_thread):
                try:
                    res = client.act(local_obs, timeout=60.0)
                    assert len(res.action) == ACT_DIM
                    with count_lock:
                        answered[0] += 1
                        n = answered[0]
                    if n >= kill_after and not killed.is_set():
                        killed.set()
                        os.kill(pids[0], signal.SIGKILL)
                        t_kill_response_mark[0] = n
                        print(
                            f"[fleet-smoke] SIGKILLed worker pid "
                            f"{pids[0]} after {n} responses"
                        )
                except Exception as e:  # noqa: BLE001 — any client
                    # failure is an accepted-request drop: a smoke fail
                    errors.append(repr(e)[:300])

        t0 = time.perf_counter()
        herd = [
            threading.Thread(target=flooder, args=(i,))
            for i in range(n_threads)
        ]
        for th in herd:
            th.start()
        for th in herd:
            th.join(timeout=600.0)
        flood_s = time.perf_counter() - t0
        offered = n_threads * per_thread
        if errors:
            fail(f"{len(errors)} dropped/errored requests: {errors[:3]}")
        if answered[0] != offered:
            fail(f"answered {answered[0]} != offered {offered}")
        if not killed.is_set():
            fail("flood finished before the kill fired; raise per_thread")
        post_kill = offered - t_kill_response_mark[0]
        if post_kill <= 0:
            fail("no goodput after the worker kill")

        health = json.loads(
            urlreq.urlopen(router + "/healthz", timeout=30).read()
        )
        if health["admitted_workers"] != 2:
            fail(f"membership never ejected the dead worker: {health}")
        dead = [
            n for n, w in health["workers"].items() if not w["admitted"]
        ]
        summary["flood"] = {
            "offered": offered,
            "answered": answered[0],
            "errors": 0,
            "responses_after_kill": post_kill,
            "goodput_rps": round(offered / flood_s, 1),
            "ejected": dead,
            "admitted_workers": health["admitted_workers"],
        }
        print(f"[fleet-smoke] flood ok: {summary['flood']}")

        # --------------------------------------- 2. rolling reload
        save_epoch(1, seed=7)
        req = urlreq.Request(
            router + "/reload", data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        roll = json.loads(urlreq.urlopen(req, timeout=120).read())["reload"]
        ok = [
            n for n, s in roll.items()
            if s.get("readmitted")
            and s.get("reload", {}).get("default", {}).get("status") == "ok"
            and s.get("reload", {}).get("default", {}).get("epoch") == 1
        ]
        if len(ok) != 2:
            fail(f"rolling reload did not roll the 2 survivors: {roll}")
        dead_status = [s for n, s in roll.items() if n in dead]
        if not dead_status or dead_status[0].get("readmitted"):
            fail(f"dead worker resurrected by the roll?: {roll}")
        # post-roll traffic serves the NEW generation
        client = PolicyClient(url=router, retries=3)
        res = client.act(obs, timeout=60.0)
        if res.generation != 1:
            fail(f"post-roll generation {res.generation} != 1")
        metrics = json.loads(
            urlreq.urlopen(router + "/metrics", timeout=30).read()
        )
        if metrics["workers_reporting"] != 2:
            fail(f"aggregated /metrics workers: {metrics.get('workers')}")
        if not metrics.get("p50_ms"):
            fail("aggregated /metrics has no merged latency percentiles")
        summary["rolling_reload"] = {
            "rolled": ok,
            "dead_worker_status": "isolated",
            "post_roll_generation": res.generation,
            "fleet_p50_ms": metrics["p50_ms"],
            "fleet_responses_total": metrics["responses_total"],
        }
        print(f"[fleet-smoke] rolling reload ok: {summary['rolling_reload']}")

        # ------------------------------------------- 3. teardown
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            fail("fleet did not exit within 120s of SIGTERM", proc)
        if rc != 0:
            fail(f"fleet exited rc={rc} after graceful SIGTERM")
        summary["teardown"] = {"rc": rc}
    finally:
        if proc.poll() is None:
            proc.kill()

    print("FLEET-SMOKE OK " + json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
