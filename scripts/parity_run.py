"""Return-parity runs: our trainer vs an independent torch SAC.

BASELINE.md's gate is "average return within ±5% of the PyTorch
baseline" at the reference run configuration. The reference itself
cannot execute in this image (it imports legacy ``gym`` and ``mpi4py``;
only gymnasium is installed), so the torch side here is an independent
PyTorch implementation of the reference's exact semantics — same
hyperparameters (ref ``main.py:147-160``: alpha=0.2 fixed, gamma=0.99,
polyak=0.995, batch 64, hidden [256,256], lr 3e-4, start_steps=
update_after=1000, update_every=50), same squashed-Gaussian math (ref
``networks/linear.py:39-51``), same per-window update burst (ref
``sac/algorithm.py:273-283``), torch-default inits (which our Flax
models also reproduce, ``models/mlp.py``).

Usage::

    python scripts/parity_run.py --impl torch --env Pendulum-v1 \
        --steps 30000 --out runs_parity/torch_pendulum.jsonl
    python scripts/parity_run.py --impl jax --env Pendulum-v1 \
        --steps 30000 --parity-pi-obs false --out ...

Each run writes one JSON line per episode (step, return) and a final
summary line; PARITY.md records the comparison.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Runnable straight from a source checkout: scripts/ is not a package.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def episode_logger(out_path):
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    f = open(out_path, "w")

    def log(record):
        f.write(json.dumps(record) + "\n")
        f.flush()

    return log


# --------------------------------------------------------------- torch side


def run_torch(env_name: str, steps: int, seed: int, out: str):
    import gymnasium
    import numpy as np
    import torch

    from torch_actor_critic_tpu.baselines import build_torch_sac

    torch.manual_seed(seed)
    np.random.seed(seed)

    env = gymnasium.make(env_name)
    obs_dim = env.observation_space.shape[0]
    act_dim = env.action_space.shape[0]
    act_limit = float(env.action_space.high[0])
    env.action_space.seed(seed)

    actor, sac_update = build_torch_sac(obs_dim, act_dim, act_limit)

    cap = min(1_000_000, steps)
    buf = {
        "s": np.zeros((cap, obs_dim), np.float32),
        "a": np.zeros((cap, act_dim), np.float32),
        "r": np.zeros(cap, np.float32),
        "s2": np.zeros((cap, obs_dim), np.float32),
        "d": np.zeros(cap, np.float32),
    }
    ptr, size = 0, 0

    batch = 64  # remaining ref hyperparams live in build_torch_sac
    start_steps, update_after, update_every = 1000, 1000, 50
    max_ep_len = 1000

    def update():
        idx = np.random.randint(0, size, batch)
        sac_update(
            *(torch.as_tensor(buf[k][idx]) for k in ("s", "a", "r", "s2", "d"))
        )

    log = episode_logger(out)
    obs, _ = env.reset(seed=seed)
    ep_ret, ep_len, returns = 0.0, 0, []
    t0 = time.time()
    for step in range(steps):
        if step < start_steps:
            action = env.action_space.sample()
        else:
            with torch.no_grad():
                action, _ = actor(torch.as_tensor(obs, dtype=torch.float32)[None])
                action = action.numpy()[0]
        obs2, r, term, trunc, _ = env.step(action)
        ep_ret += r
        ep_len += 1
        hit_cap = ep_len >= max_ep_len
        buf["s"][ptr] = obs; buf["a"][ptr] = action; buf["r"][ptr] = r
        buf["s2"][ptr] = obs2
        buf["d"][ptr] = float(term and not hit_cap)
        ptr = (ptr + 1) % cap
        size = min(size + 1, cap)
        obs = obs2
        if term or trunc or hit_cap:
            returns.append(ep_ret)
            log({"step": step, "episode_return": ep_ret, "len": ep_len})
            obs, _ = env.reset()
            ep_ret, ep_len = 0.0, 0
        if step >= update_after and (step + 1) % update_every == 0:
            for _ in range(update_every):
                update()

    # deterministic eval, 10 episodes
    eval_returns = []
    for _ in range(10):
        o, _ = env.reset()
        ret, done, n = 0.0, False, 0
        while not done and n < max_ep_len:
            with torch.no_grad():
                a, _ = actor(
                    torch.as_tensor(o, dtype=torch.float32)[None],
                    deterministic=True,
                )
            o, r, term, trunc, _ = env.step(a.numpy()[0])
            ret += r; n += 1; done = term or trunc
        eval_returns.append(ret)
    summary = {
        "summary": True, "impl": "torch", "env": env_name, "steps": steps,
        "seed": seed,
        "train_return_last25pct": float(
            np.mean(returns[-max(1, len(returns) // 4):])
        ),
        "eval_return_mean": float(np.mean(eval_returns)),
        "eval_return_std": float(np.std(eval_returns)),
        "wall_s": round(time.time() - t0, 1),
    }
    log(summary)
    print(json.dumps(summary), flush=True)


# ----------------------------------------------------------------- jax side


def run_jax(env_name: str, steps: int, seed: int, out: str, parity_pi_obs: bool):
    import jax

    # Honor JAX_PLATFORMS=cpu even when a sitecustomize hook re-registers
    # an accelerator platform over it (same countermeasure as bench.py).
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        jax.config.update("jax_platforms", "cpu")

    from torch_actor_critic_tpu.parallel import make_mesh
    from torch_actor_critic_tpu.sac.trainer import Trainer
    from torch_actor_critic_tpu.utils.config import SACConfig

    steps_per_epoch = 5000
    epochs = max(1, steps // steps_per_epoch)
    actual_steps = epochs * steps_per_epoch
    if actual_steps != steps:
        print(
            f"[parity] NOTE: --steps {steps} rounded to {actual_steps} "
            f"({epochs} epochs x {steps_per_epoch}); the summary records "
            "the ACTUAL step count.",
            file=sys.stderr,
        )
    cfg = SACConfig(
        epochs=epochs,
        steps_per_epoch=steps_per_epoch,
        parity_pi_obs=parity_pi_obs,
        max_ep_len=1000,
        buffer_size=min(1_000_000, actual_steps),
    )
    t0 = time.time()
    tr = Trainer(env_name, cfg, mesh=make_mesh(dp=1), seed=seed)
    log = episode_logger(out)

    metrics = tr.train()
    ev = tr.evaluate(episodes=10, deterministic=True)
    summary = {
        "summary": True, "impl": "jax", "env": env_name,
        "steps": actual_steps,
        "seed": seed, "parity_pi_obs": parity_pi_obs,
        "train_return_final_epoch": metrics["reward"],
        "eval_return_mean": ev["ep_ret_mean"],
        "eval_return_std": ev["ep_ret_std"],
        "grad_steps_per_sec": metrics.get("grad_steps_per_sec"),
        "env_steps_per_sec": metrics.get("env_steps_per_sec"),
        "wall_s": round(time.time() - t0, 1),
    }
    log(summary)
    tr.close()
    print(json.dumps(summary), flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--impl", choices=["torch", "jax"], required=True)
    p.add_argument("--env", default="Pendulum-v1")
    p.add_argument("--steps", type=int, default=30000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", required=True)
    p.add_argument("--parity-pi-obs", default="false",
                   choices=["true", "false"])
    args = p.parse_args()
    if args.impl == "torch":
        run_torch(args.env, args.steps, args.seed, args.out)
    else:
        run_jax(
            args.env, args.steps, args.seed, args.out,
            parity_pi_obs=args.parity_pi_obs == "true",
        )


if __name__ == "__main__":
    sys.exit(main())
