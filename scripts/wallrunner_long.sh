#!/usr/bin/env bash
# Standalone wallrunner-long runner with 20-min periodic commits (the
# round5_longruns2.sh tail, re-launched after the learn_alpha preset
# fix; the trend must survive a wall-clock cutoff).
set -u
cd "$(dirname "$0")/.."
export TAC_BENCH_PLATFORM=cpu JAX_PLATFORMS=cpu

echo "[wallrunner] starting at $(date -u +%FT%TZ)"
python scripts/evidence_run.py wallrunner-long &
train_pid=$!
(
    while kill -0 "$train_pid" 2>/dev/null; do
        sleep 1200
        git add runs/wallrunner-long 2>/dev/null
        git commit -q -m "wallrunner-long: periodic metrics snapshot" \
            -- runs/wallrunner-long 2>/dev/null \
            && echo "[wallrunner] periodic commit"
    done
) &
if wait "$train_pid"; then
    git add runs/wallrunner-long 2>/dev/null
    git commit -q -m "Wall-runner long run: parallel pool, committed trend" \
        -- runs/wallrunner-long 2>/dev/null \
        && echo "[wallrunner] committed final"
else
    echo "[wallrunner] FAILED or cut off (partial metrics committed above)"
fi
echo "[wallrunner] done at $(date -u +%FT%TZ)"
