#!/usr/bin/env python
"""Elastic self-healing fleet smoke (``make elastic-smoke``).

Proves the PR-20 elastic plane end-to-end with REAL processes
(docs/RESILIENCE.md "Elasticity"), both planes:

Serving plane (serve.py --fleet 1 --warm-pool 2 --obs --elastic on):

1. a single-worker fleet comes up behind the router with two pre-forked
   warm spares and a tight ``goodput_floor`` SLO rule;
2. a flood arms the rule, stopping it breaches -> the controller's
   scale-out draws a warm spare and admits it through router
   membership (fleet /metrics: ``elastic.scale_out_total`` >= 1);
3. the flood resumes (spike) and one worker is SIGKILLed mid-spike:
   the warm-pool monitor replaces it, the rule recovers (counted
   ``slo_recovered``), and the counting load loops observe ZERO
   dropped requests across the kill;
4. the flood drops to a trickle: green windows accumulate and the
   controller scales back in by DRAIN (never a kill) — still zero
   drops — then SIGTERM exports a Perfetto timeline whose elastic
   lane (pid 6) carries the scale_out and scale_in decision spans.

Training plane (train.py --decoupled --actors 2 --elastic on
--actor-max-restarts 0):

5. an actor is SIGKILLed; with a zero restart budget the supervisor
   gives up and the trainer DEGRADES to the surviving slice at the
   next epoch boundary (conservation stays green — the dead actor's
   staged tail is the invariant's dropped_dead_actor term);
6. after ``elastic_readmit_epochs`` the slot is re-admitted with a
   fresh budget and bumped incarnation; metrics.jsonl shows the
   degraded window close (``elastic/degraded_slots`` back to 0),
   telemetry.jsonl carries schema-valid ``elastic_decision`` events
   for BOTH edges, and the exported trace has the degrade/readmit
   spans on the elastic lane's train track.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error as urlerr
import urllib.request as urlreq
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

OBS_DIM = 3   # Pendulum-v1
ACT_DIM = 1

DECISION_KEYS = ("seq", "plane", "action", "reason", "replicas_before",
                 "replicas_after", "outcome")


def log(msg):
    print(f"[elastic-smoke] {msg}", flush=True)


def fail(msg):
    log(f"FAIL: {msg}")
    sys.exit(1)


def wait_for(predicate, what, timeout_s=300.0, poll_s=0.25):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(poll_s)
    fail(f"timed out after {timeout_s:.0f}s waiting for {what}")


def get_json(url, timeout=3):
    try:
        with urlreq.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:  # noqa: BLE001 - polling probe
        return None


def jsonl(path: Path):
    if not path.exists():
        return []
    out = []
    for line in path.read_text().splitlines():
        try:
            out.append(json.loads(line))
        except ValueError:
            pass
    return out


def build_checkpoint(ckpt_dir):
    """A serve-able SAC checkpoint without a training run."""
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.models import Actor, DoubleCritic
    from torch_actor_critic_tpu.sac import SAC
    from torch_actor_critic_tpu.utils.checkpoint import Checkpointer
    from torch_actor_critic_tpu.utils.config import SACConfig

    cfg = SACConfig(hidden_sizes=(16, 16))
    sac = SAC(
        cfg, Actor(act_dim=ACT_DIM, hidden_sizes=(16, 16)),
        DoubleCritic(hidden_sizes=(16, 16)), ACT_DIM,
    )
    state = sac.init_state(jax.random.key(0), jnp.zeros((OBS_DIM,)))
    ck = Checkpointer(ckpt_dir, save_buffer=False)
    ck.save(0, state, extra={"config": cfg.to_json()}, wait=True)
    ck.close()


def start_elastic_fleet(ckpt_dir, slo_path, trace_path, env):
    """serve.py --fleet 1 --warm-pool 2 --elastic on; returns
    (proc, startup dict)."""
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "serve.py"),
         "--ckpt-dir", ckpt_dir,
         "--obs-dim", str(OBS_DIM), "--act-dim", str(ACT_DIM),
         "--fleet", "1", "--port", "0", "--router-poll", "0.5",
         "--warm-pool", "2",
         "--obs", "--obs-interval", "0.5",
         "--slo-config", str(slo_path),
         "--elastic", "on",
         "--elastic-min", "1", "--elastic-max", "2",
         "--elastic-out-cooldown", "2.0",
         "--elastic-in-cooldown", "8.0",
         "--elastic-in-windows", "6",
         "--trace-export", str(trace_path),
         "--max-batch", "4", "--max-wait-ms", "2",
         "--poll-interval", "0"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    startup = None
    deadline = time.time() + 600
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                fail(f"fleet died rc={proc.returncode} before ready")
            time.sleep(0.1)
            continue
        sys.stderr.write(f"[fleet] {line}")
        if line.startswith("{"):
            try:
                startup = json.loads(line)
                if "router" in startup:
                    break
            except json.JSONDecodeError:
                continue
    if startup is None:
        fail("the fleet never printed its startup JSON")
    threading.Thread(
        target=lambda: [None for _ in proc.stdout], daemon=True
    ).start()
    return proc, startup


def serving_phase(tmp, ckpt_dir, env):
    """Scale-out on breach, kill-mid-spike with zero drops + counted
    recovery, drain-based scale-in, elastic spans in the trace."""
    trace_path = tmp / "serve_trace.json"
    # The rule NAME must be in ElasticPolicy.scale_out_rules — that is
    # how a breach becomes a spawn. Arm-on-first-pass: nothing fires
    # until the flood starts.
    slo_path = tmp / "slo.json"
    slo_path.write_text(json.dumps([{
        "name": "goodput_floor", "path": "router.requests_per_sec",
        "op": "min", "threshold": 0.5,
        "breach_windows": 2, "recover_windows": 2,
    }]))

    log("serving phase: fleet (1 worker + 2 warm spares, elastic on)")
    fleet, startup = start_elastic_fleet(ckpt_dir, slo_path, trace_path, env)
    if startup.get("elastic") != "on":
        fail(f"startup JSON does not confirm elastic: {startup}")
    router = startup["router"]
    obs_url = startup["obs"]
    if not obs_url:
        fail("startup JSON carries no obs collector address")
    initial_pids = startup["pids"]

    flood_stop = threading.Event()
    flood_level = [0]  # thread i floods only while i < flood_level[0]
    drops = []  # each entry: one hard client-visible failure

    def load_loop(i):
        body = json.dumps(
            {"obs": [0.1] * OBS_DIM, "deterministic": True}
        ).encode()
        while not flood_stop.is_set():
            if i >= flood_level[0]:
                time.sleep(0.05)
                continue
            req = urlreq.Request(
                router + "/act", data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                urlreq.urlopen(req, timeout=30).read()
            except urlerr.HTTPError as e:
                if e.code != 503:  # shed is backpressure, not a drop
                    drops.append(f"thread {i}: HTTP {e.code}")
                time.sleep(0.1)
            except Exception as e:  # noqa: BLE001 - the drop counter
                drops.append(f"thread {i}: {type(e).__name__}: {e}")
                time.sleep(0.1)

    threads = [
        threading.Thread(target=load_loop, args=(i,), daemon=True)
        for i in range(3)
    ]
    for th in threads:
        th.start()

    def fleet_section():
        m = get_json(router + "/metrics")
        return None if m is None else m.get("fleet")

    def rule_state():
        m = get_json(obs_url + "/metrics")
        if m is None:
            return None
        return m["slo"]["rules"]["goodput_floor"]

    def reporting():
        m = get_json(router + "/metrics")
        return -1 if m is None else m.get("workers_reporting", -1)

    try:
        wait_for(lambda: reporting() == 1, "the initial worker")
        wait_for(lambda: get_json(obs_url + "/metrics") is not None,
                 "the obs collector endpoint")

        # Satellite pin: the fleet /metrics section carries warm-pool
        # spare readiness + last-refill status alongside the
        # scaler/controller counters.
        fl = wait_for(fleet_section, "the fleet /metrics section")
        for key in ("warm_pool", "scaler", "elastic"):
            if key not in fl:
                fail(f"fleet /metrics section is missing {key!r}: {fl}")
        for key in ("ready", "last_refill_ok", "last_refill_age_s"):
            if key not in fl["warm_pool"]:
                fail(f"warm_pool status is missing {key!r}: "
                     f"{fl['warm_pool']}")
        wait_for(lambda: (f := fleet_section()) is not None
                 and f["warm_pool"]["ready"] >= 1,
                 "a warm spare to become ready")

        log("flood on (arm the goodput rule) ...")
        flood_level[0] = 3
        wait_for(lambda: (st := rule_state()) is not None and st["armed"],
                 "the goodput_floor rule to arm")

        log("flood off (breach -> elastic scale-out) ...")
        flood_level[0] = 0
        wait_for(lambda: (st := rule_state()) is not None
                 and st["breached"], "the slo_breach")
        wait_for(lambda: (f := fleet_section()) is not None
                 and f["elastic"]["scale_out_total"] >= 1
                 and f["scaler"]["spawned_total"] >= 1,
                 "the controller's scale-out decision")
        wait_for(lambda: reporting() == 2,
                 "the drawn spare to join the fleet")
        log("scale-out confirmed: 2 workers reporting")

        log("flood on + SIGKILL a worker mid-spike ...")
        flood_level[0] = 3
        time.sleep(0.5)  # let the spike land on both workers
        os.kill(initial_pids[0], signal.SIGKILL)
        wait_for(lambda: (st := rule_state()) is not None
                 and not st["breached"]
                 and st["recoveries_total"] >= 1,
                 "the counted slo_recovered")
        # The monitor's warm-spare replacement restores the fleet.
        wait_for(lambda: reporting() == 2,
                 "the kill-replacement spare")
        if drops:
            fail(f"{len(drops)} dropped requests across the kill "
                 f"(first: {drops[0]})")
        log("recovery confirmed: worker killed mid-spike, zero drops, "
            "slo_recovered counted")

        log("trickle load (green windows -> drain-based scale-in) ...")
        flood_level[0] = 1
        wait_for(lambda: (f := fleet_section()) is not None
                 and f["elastic"]["scale_in_total"] >= 1
                 and f["scaler"]["drained_total"] >= 1,
                 "the controller's scale-in decision", timeout_s=300)
        wait_for(lambda: reporting() == 1,
                 "the drained worker to leave membership")
        if drops:
            fail(f"scale-in dropped {len(drops)} accepted requests "
                 f"(first: {drops[0]})")
        fl = fleet_section()
        if fl["scaler"]["force_kills_total"] != 0:
            fail(f"scale-in escalated to force-kill: {fl['scaler']}")
        log("scale-in confirmed: drain-based, zero drops")
    finally:
        flood_stop.set()
        if fleet.poll() is None:
            fleet.send_signal(signal.SIGTERM)
        try:
            fleet.wait(timeout=120)
        except subprocess.TimeoutExpired:
            fleet.kill()

    # The exported timeline: decision spans on the elastic lane.
    if not trace_path.exists():
        fail("the fleet exported no trace")
    trace = json.loads(trace_path.read_text())["traceEvents"]
    elastic_spans = [
        e for e in trace if e.get("ph") == "B" and e.get("pid") == 6
    ]
    names = {e["name"] for e in elastic_spans}
    if "elastic scale_out" not in names or "elastic scale_in" not in names:
        fail(f"elastic lane is missing decision spans: {sorted(names)}")
    for e in elastic_spans:
        missing = [k for k in ("action", "plane", "outcome", "seq")
                   if k not in e.get("args", {})]
        if missing:
            fail(f"elastic span {e['name']} args missing {missing}")
    log(f"serve trace OK: {len(elastic_spans)} decision spans on the "
        f"elastic lane ({sorted(names)})")
    return drops


def training_phase(tmp, env):
    """Actor SIGKILL -> degrade to surviving slice (conservation
    green) -> readmit at an epoch boundary with a bumped incarnation."""
    runs_root = tmp / "runs"
    trace_path = tmp / "train_trace.json"
    log("training phase: fleet learner (--actors 2 --elastic on, "
        "zero restart budget)")
    learner = subprocess.Popen(
        [sys.executable, "-m", "torch_actor_critic_tpu.train",
         "--environment", "Pendulum-v1",
         "--hidden-sizes", "16,16", "--batch-size", "16",
         "--epochs", "120", "--steps-per-epoch", "100",
         "--start-steps", "20", "--update-after", "20",
         "--update-every", "20", "--buffer-size", "2000",
         "--max-ep-len", "100",
         "--decoupled", "true", "--actors", "2",
         "--actor-max-restarts", "0",
         "--elastic", "on", "--elastic-readmit-epochs", "1",
         "--telemetry", "true",
         "--trace-export", str(trace_path),
         "--runs-root", str(runs_root), "--experiment", "elastic"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )

    try:
        run_dir = wait_for(
            lambda: next(iter((runs_root / "elastic").glob("*")), None),
            "the learner run dir",
        )

        # The per-epoch "fleet" telemetry event carries the supervisor
        # stats — actor id -> {incarnation, pid, alive} — which is how
        # an operator (and this harness) maps a slot to a killable pid.
        def fleet_events():
            return [e for e in jsonl(run_dir / "telemetry.jsonl")
                    if e.get("type") == "fleet"]

        def live_actors():
            evs = fleet_events()
            if not evs:
                return None
            actors = evs[-1].get("supervisor", {}).get("actors", {})
            live = {aid: a for aid, a in actors.items()
                    if a.get("alive") and a.get("pid")}
            return live if len(live) >= 2 else None

        actors = wait_for(live_actors,
                          "both actors alive in the fleet telemetry",
                          timeout_s=600)
        wait_for(lambda: len(jsonl(run_dir / "metrics.jsonl")) >= 1,
                 "the first epoch metrics line")

        victim_aid = sorted(actors)[0]
        victim_pid = actors[victim_aid]["pid"]
        log(f"SIGKILL actor {victim_aid} (pid {victim_pid}) ...")
        os.kill(victim_pid, signal.SIGKILL)

        def degraded_row():
            rows = jsonl(run_dir / "metrics.jsonl")
            return next((r for r in rows
                         if r.get("elastic/degraded_slots", 0) >= 1), None)

        row = wait_for(degraded_row,
                       "the degrade edge in metrics.jsonl", timeout_s=600)
        if row.get("decoupled/conservation_ok") != 1.0:
            fail(f"conservation broke across the degrade: {row}")
        log(f"degraded to the surviving slice at step "
            f"{row.get('step')} with conservation green")

        def restored_row():
            rows = jsonl(run_dir / "metrics.jsonl")
            return next((r for r in rows
                         if r.get("elastic/readmit_total", 0) >= 1
                         and r.get("elastic/degraded_slots", 1) == 0), None)

        row = wait_for(restored_row,
                       "the readmit edge in metrics.jsonl", timeout_s=600)
        if row.get("decoupled/conservation_ok") != 1.0:
            fail(f"conservation broke across the readmit: {row}")

        def readmitted_incarnation():
            evs = fleet_events()
            if not evs:
                return None
            a = evs[-1].get("supervisor", {}).get(
                "actors", {}).get(victim_aid, {})
            return a if a.get("incarnation", 0) >= 1 else None

        a = wait_for(readmitted_incarnation,
                     "the re-admitted actor's bumped incarnation",
                     timeout_s=600)
        log(f"slot {victim_aid} re-admitted at step {row.get('step')} "
            f"(incarnation {a['incarnation']})")

        log("SIGTERM the learner; expect the trace export ...")
        learner.send_signal(signal.SIGTERM)
        rc = learner.wait(timeout=600)
        if rc not in (0, 75):
            fail(f"learner exited rc={rc}, expected 0 or requeue 75")
    finally:
        if learner.poll() is None:
            learner.send_signal(signal.SIGTERM)
            try:
                learner.wait(timeout=120)
            except subprocess.TimeoutExpired:
                learner.kill()

    # Schema-valid decision events for BOTH edges.
    events = jsonl(run_dir / "telemetry.jsonl")
    decisions = [e for e in events if e.get("type") == "elastic_decision"]
    actions = {e.get("action") for e in decisions}
    if "degrade" not in actions or "readmit" not in actions:
        fail(f"telemetry.jsonl decision actions: {sorted(actions)} "
             f"(wanted degrade + readmit)")
    for e in decisions:
        missing = [k for k in DECISION_KEYS if k not in e]
        if missing:
            fail(f"elastic_decision event missing {missing}: {e}")
    degrade = next(e for e in decisions if e["action"] == "degrade")
    readmit = next(e for e in decisions if e["action"] == "readmit")
    if degrade["time"] >= readmit["time"]:
        fail("degrade did not precede readmit")

    # The train track of the elastic lane in the exported trace.
    if not trace_path.exists():
        fail("the learner exported no trace")
    trace = json.loads(trace_path.read_text())["traceEvents"]
    train_spans = [
        e for e in trace
        if e.get("ph") == "B" and e.get("pid") == 6
    ]
    names = {e["name"] for e in train_spans}
    if "elastic degrade" not in names or "elastic readmit" not in names:
        fail(f"train elastic lane is missing spans: {sorted(names)}")
    log(f"train trace OK: {len(train_spans)} decision spans "
        f"({sorted(names)})")
    return decisions


def main():
    tmp = Path(tempfile.mkdtemp(prefix="elastic_smoke_"))
    ckpt_dir = str(tmp / "ckpts")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    log("building a serve-able checkpoint ...")
    build_checkpoint(ckpt_dir)

    serving_phase(tmp, ckpt_dir, env)
    training_phase(tmp, env)

    log("ALL OK: breach-driven scale-out from the warm pool, a "
        "mid-spike SIGKILL absorbed with zero dropped requests and a "
        "counted recovery, drain-based scale-in, and a training-plane "
        "degrade/readmit cycle with conservation green — every "
        "decision a schema-valid event on the Perfetto elastic lane")


if __name__ == "__main__":
    main()
